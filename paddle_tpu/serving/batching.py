"""Request futures + the dynamic batcher.

Orca/Clipper-style coalescing: concurrent submitters enqueue
row-oriented requests into a BOUNDED queue; the server's worker pulls a
first request, then keeps absorbing arrivals until either
``max_batch_size`` rows are gathered or ``batch_timeout_ms`` has passed
since the batch opened — whichever fires first.  A request that would
overflow the open batch is carried into the next one (never split).

Admission control lives at the queue: a full queue sheds the request
with a typed ServerOverloaded at submit time, so overload back-pressure
reaches the caller immediately instead of growing an unbounded backlog.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from paddle_tpu.serving.errors import DeadlineExceeded, ServerOverloaded

__all__ = ["ServingRequest", "DynamicBatcher"]

# safety-net wait bound while parked on the empty-queue condition: every
# real wakeup is a notify (offer() on arrival, wake() on shutdown), so
# an idle server sleeps — this only bounds the damage of a lost notify
_IDLE_WAIT_S = 0.5


class ServingRequest:
    """One submitted inference request: a row-oriented feed plus a
    future the submitter waits on.  ``n_rows`` is the leading dim shared
    by every feed array (validated by the server at submit).

    ``trace_id`` (optional) is the request's Dapper-style trace id:
    every span recorded while the batch containing this request executes
    carries it (``monitor.trace_context``), and the flight recorder keys
    its tail-sampled record by it.  ``parent_span`` (optional) is the
    submitter-side span id the request's own spans hang under — the
    client's infer span in-process, or the wire server's request span
    when the request arrived over a transport hop."""

    def __init__(self, feed: Dict[str, np.ndarray], n_rows: int,
                 deadline: Optional[float] = None,
                 trace_id: Optional[str] = None,
                 parent_span: Optional[str] = None):
        self.feed = feed
        self.n_rows = n_rows
        self.deadline = deadline  # time.monotonic() deadline, or None
        self.trace_id = trace_id
        self.parent_span = parent_span
        self.submit_t = time.perf_counter()
        self._done = threading.Event()
        self._value: Optional[List[np.ndarray]] = None
        self._exc: Optional[BaseException] = None

    # --- producer (worker) side ---
    def complete(self, value: List[np.ndarray]) -> None:
        if self._done.is_set():
            return  # first completion wins (shutdown races)
        self._value = value
        self._done.set()

    def fail(self, exc: BaseException) -> None:
        if self._done.is_set():
            return  # first completion wins (shutdown races)
        self._exc = exc
        self._done.set()

    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline is not None and (now or time.monotonic()) >= self.deadline

    # --- consumer (submitter) side ---
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> List[np.ndarray]:
        """Block for the result.  Honors the request deadline even when
        the server never gets to this request (a deadline must surface
        as a typed error, never a hang)."""
        if timeout is None and self.deadline is not None:
            timeout = max(0.0, self.deadline - time.monotonic())
        if not self._done.wait(timeout):
            raise DeadlineExceeded(
                "no result within %.1f ms" % ((timeout or 0.0) * 1e3))
        if self._exc is not None:
            raise self._exc
        assert self._value is not None
        return self._value


class DynamicBatcher:
    """Bounded request queue + the coalescing policy.

    The queue is a deque under one condition variable: submitters
    ``notify`` on arrival and the (single) consuming worker WAITS on the
    condition while idle — an idle server sleeps at ~0% CPU instead of
    polling (the pre-CV version woke 50x/s to re-check a stop flag).
    ``wake()`` nudges a parked consumer at shutdown."""

    def __init__(self, max_batch_size: int, batch_timeout_ms: float,
                 queue_capacity: int):
        self.max_batch_size = int(max_batch_size)
        self.batch_timeout_s = float(batch_timeout_ms) / 1e3
        # queue.Queue convention the pre-deque version had: <= 0 means
        # unbounded, not "shed everything"
        self._capacity = int(queue_capacity) if int(queue_capacity) > 0 else None
        self._cv = threading.Condition()
        self._dq: "deque[ServingRequest]" = deque()
        self._carry: Optional[ServingRequest] = None  # worker-thread only

    def qsize(self) -> int:
        return len(self._dq) + (1 if self._carry is not None else 0)

    # --- submitter side ---
    def offer(self, req: ServingRequest) -> None:
        with self._cv:
            if self._capacity is not None and len(self._dq) >= self._capacity:
                raise ServerOverloaded(
                    "request queue full (%d waiting); shedding"
                    % len(self._dq)) from None
            self._dq.append(req)
            self._cv.notify()

    def wake(self) -> None:
        """Wake a consumer parked on the empty-queue wait (shutdown)."""
        with self._cv:
            self._cv.notify_all()

    def drain_pending(self) -> List[ServingRequest]:
        """Pop and return every queued-but-unbatched request (shutdown
        without drain: the server fails them with ServerClosed).  Does
        not touch the carry slot — that one is the worker's."""
        with self._cv:
            out = list(self._dq)
            self._dq.clear()
        return out

    # --- worker side (single consumer) ---
    def _take_first(self, stop: threading.Event, on_expired,
                    block: bool = True) -> Optional[ServingRequest]:
        if self._carry is not None:
            first, self._carry = self._carry, None
            if not first.expired():
                return first
            on_expired(first)
        while True:
            with self._cv:
                while not self._dq:
                    if not block or stop.is_set():
                        return None  # nothing ready / drained
                    # sleeps until offer()/wake() notifies; the timeout
                    # is only a lost-notify safety net, not a poll
                    self._cv.wait(timeout=_IDLE_WAIT_S)
                first = self._dq.popleft()
            if first.expired():
                on_expired(first)
                continue
            return first

    def next_batch(self, stop: threading.Event, on_expired,
                   block: bool = True) -> Optional[List[ServingRequest]]:
        """Return the next coalesced batch, or None once stopped AND
        drained.  ``on_expired`` is called with each request whose
        deadline passed while queued (the server fails + counts it).

        ``block=False``: a non-blocking poll — returns None immediately
        when no live request is ready.

        While draining (``stop`` set) the window is not awaited — only
        already-queued requests coalesce, so shutdown latency is bounded
        by the in-flight work, not by the timeout."""
        first = self._take_first(stop, on_expired, block=block)
        if first is None:
            return None
        batch = [first]
        rows = first.n_rows
        window_end = time.monotonic() + self.batch_timeout_s
        while rows < self.max_batch_size:
            with self._cv:
                if not self._dq:
                    wait = window_end - time.monotonic()
                    if wait <= 0 or stop.is_set():
                        break
                    self._cv.wait(timeout=wait)
                    if not self._dq:
                        continue  # window re-checked at loop top
                req = self._dq.popleft()
            if req.expired():
                on_expired(req)
                continue
            if rows + req.n_rows > self.max_batch_size:
                self._carry = req  # never split a request across batches
                break
            batch.append(req)
            rows += req.n_rows
        return batch
