"""Request futures + the dynamic batcher.

Orca/Clipper-style coalescing: concurrent submitters enqueue
row-oriented requests into a BOUNDED queue; the server's worker pulls a
first request, then keeps absorbing arrivals until either
``max_batch_size`` rows are gathered or ``batch_timeout_ms`` has passed
since the batch opened — whichever fires first.  A request that would
overflow the open batch is carried into the next one (never split).

Admission control lives at the queue, but is no longer a fixed FIFO
(``serving.admission``): the store is deadline-ordered (EDF) with an
expired-entry sweep, the bound adapts by AIMD on the observed queue
wait, and a full queue sheds by PRIORITY — a low-priority queued entry
is evicted for a more important arrival, and whoever is shed gets a
typed ``ServerOverloaded`` carrying a computed ``retry_after_ms`` hint,
so overload back-pressure reaches callers immediately with a usable
pacing signal instead of growing an unbounded backlog.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from paddle_tpu.serving.admission import PRIORITY_NORMAL, AdmissionQueue
from paddle_tpu.serving.errors import DeadlineExceeded, ServerOverloaded

__all__ = ["ServingRequest", "DynamicBatcher"]

# safety-net wait bound while parked on the empty-queue condition: every
# real wakeup is a notify (offer() on arrival, wake() on shutdown), so
# an idle server sleeps — this only bounds the damage of a lost notify
_IDLE_WAIT_S = 0.5


class ServingRequest:
    """One submitted inference request: a row-oriented feed plus a
    future the submitter waits on.  ``n_rows`` is the leading dim shared
    by every feed array (validated by the server at submit).

    ``priority`` is the request's admission class (lower = more
    important; ``serving.admission.PRIORITY_*``): under overload the
    queue sheds strictly-lower-priority entries first.

    ``trace_id`` (optional) is the request's Dapper-style trace id:
    every span recorded while the batch containing this request executes
    carries it (``monitor.trace_context``), and the flight recorder keys
    its tail-sampled record by it.  ``parent_span`` (optional) is the
    submitter-side span id the request's own spans hang under — the
    client's infer span in-process, or the wire server's request span
    when the request arrived over a transport hop.

    ``precision`` (optional) is the request's compiled-variant choice
    on a mixed-precision endpoint: None serves the endpoint's policy
    default, ``"fp32"`` is the per-request opt-out.  A batch is always
    ONE variant — the coalescing loop never mixes precisions."""

    def __init__(self, feed: Dict[str, np.ndarray], n_rows: int,
                 deadline: Optional[float] = None,
                 trace_id: Optional[str] = None,
                 parent_span: Optional[str] = None,
                 priority: int = PRIORITY_NORMAL,
                 precision: Optional[str] = None):
        self.feed = feed
        self.n_rows = n_rows
        self.deadline = deadline  # time.monotonic() deadline, or None
        self.priority = int(priority)
        self.trace_id = trace_id
        self.parent_span = parent_span
        self.precision = precision
        self.submit_t = time.perf_counter()
        self.done_t: Optional[float] = None  # perf_counter at completion
        self._done = threading.Event()
        self._value: Optional[List[np.ndarray]] = None
        self._exc: Optional[BaseException] = None

    # --- producer (worker) side ---
    def complete(self, value: List[np.ndarray]) -> None:
        if self._done.is_set():
            return  # first completion wins (shutdown races)
        self._value = value
        self.done_t = time.perf_counter()
        self._done.set()

    def fail(self, exc: BaseException) -> None:
        if self._done.is_set():
            return  # first completion wins (shutdown races)
        self._exc = exc
        self.done_t = time.perf_counter()
        self._done.set()

    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline is not None and (now or time.monotonic()) >= self.deadline

    # --- consumer (submitter) side ---
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> List[np.ndarray]:
        """Block for the result.  Honors the request deadline even when
        the server never gets to this request (a deadline must surface
        as a typed error, never a hang)."""
        if timeout is None and self.deadline is not None:
            timeout = max(0.0, self.deadline - time.monotonic())
        if not self._done.wait(timeout):
            raise DeadlineExceeded(
                "no result within %.1f ms" % ((timeout or 0.0) * 1e3))
        if self._exc is not None:
            raise self._exc
        assert self._value is not None
        return self._value


class DynamicBatcher:
    """Bounded request queue + the coalescing policy.

    The store is an ``AdmissionQueue`` (EDF heap, priority shedding,
    AIMD admit limit) under one condition variable: submitters
    ``notify`` on arrival and the (single) consuming worker WAITS on the
    condition while idle — an idle server sleeps at ~0% CPU instead of
    polling.  ``wake()`` nudges a parked consumer at shutdown.

    ``eager`` (set by the server's brownout ladder at level >= 2)
    collapses the coalescing window to 0: whatever is queued ships
    immediately — under saturation the window only adds latency, the
    queue itself provides the batching.

    ``on_shed(req, retry_after_ms)`` / ``on_expired(req)`` are the
    server's hooks for requests the QUEUE drops (priority eviction /
    offer-time sweep); the defaults fail the request typed so a
    standalone batcher still honors the contract."""

    def __init__(self, max_batch_size: int, batch_timeout_ms: float,
                 queue_capacity: int, name: str = "server",
                 target_wait_ms: float = 50.0, min_limit: int = 4,
                 adaptive: bool = True, class_weights="default"):
        self.max_batch_size = int(max_batch_size)
        self.batch_timeout_s = float(batch_timeout_ms) / 1e3
        self.queue = AdmissionQueue(
            queue_capacity, target_wait_ms=target_wait_ms,
            min_limit=min_limit, name=name, adaptive=adaptive,
            class_weights=class_weights)
        self._cv = self.queue.cv  # one lock: queue state + wakeups
        self._carry: Optional[ServingRequest] = None  # worker-thread only
        self.eager = False
        self.on_shed = self._default_shed
        self.on_expired = self._default_expired

    @staticmethod
    def _default_shed(req: ServingRequest, retry_after_ms: float) -> None:
        req.fail(ServerOverloaded(
            "evicted by a higher-priority request",
            retry_after_ms=retry_after_ms))

    @staticmethod
    def _default_expired(req: ServingRequest) -> None:
        req.fail(DeadlineExceeded("deadline passed while queued"))

    def qsize(self) -> int:
        return self.queue.qsize() + (1 if self._carry is not None else 0)

    def depth_ratio(self) -> float:
        """Queue pressure for the brownout controller."""
        return self.queue.depth_ratio()

    # --- submitter side ---
    def offer(self, req: ServingRequest) -> None:
        admitted, expired, shed, retry_ms = self.queue.offer(req)
        for r in expired:
            self.on_expired(r)
        for r in shed:
            self.on_shed(r, retry_ms)
        if not admitted:
            raise ServerOverloaded(
                "request queue at its admit limit (%d); shedding"
                % self.queue.limit, retry_after_ms=retry_ms) from None

    def wake(self) -> None:
        """Wake a consumer parked on the empty-queue wait (shutdown)."""
        with self._cv:
            self._cv.notify_all()

    def drain_pending(self) -> List[ServingRequest]:
        """Pop and return every queued-but-unbatched request (shutdown
        without drain: the server fails them with ServerClosed).  Does
        not touch the carry slot — that one is the worker's."""
        with self._cv:
            return self.queue.drain_locked()

    def close(self) -> None:
        """Retire the queue's gauge series (server stop)."""
        self.queue.close()

    # --- worker side (single consumer) ---
    def _take_first(self, stop: threading.Event, on_expired,
                    block: bool = True) -> Optional[ServingRequest]:
        if self._carry is not None:
            first, self._carry = self._carry, None
            if not first.expired():
                return first
            on_expired(first)
        while True:
            expired: List[ServingRequest] = []
            with self._cv:
                while True:
                    req, ex = self.queue.pop_locked()
                    expired.extend(ex)
                    if req is not None or expired:
                        break
                    if not block or stop.is_set():
                        break
                    # sleeps until offer()/wake() notifies; the timeout
                    # is only a lost-notify safety net, not a poll
                    self._cv.wait(timeout=_IDLE_WAIT_S)
            for r in expired:
                on_expired(r)
            if req is not None:
                return req
            if expired:
                continue  # swept some; go park again for live work
            return None  # nothing ready / drained

    def next_batch(self, stop: threading.Event, on_expired,
                   block: bool = True) -> Optional[List[ServingRequest]]:
        """Return the next coalesced batch, or None once stopped AND
        drained.  ``on_expired`` is called with each request whose
        deadline passed while queued (the server fails + counts it).

        Requests coalesce in DEADLINE order (the queue is EDF), so the
        batch always starts from the request closest to giving up.

        ``block=False``: a non-blocking poll — returns None immediately
        when no live request is ready.

        While draining (``stop`` set) or in ``eager`` brownout mode the
        window is not awaited — only already-queued requests coalesce,
        so shutdown latency is bounded by the in-flight work and a
        saturated server ships what it has."""
        first = self._take_first(stop, on_expired, block=block)
        if first is None:
            return None
        batch = [first]
        rows = first.n_rows
        window = 0.0 if self.eager else self.batch_timeout_s
        window_end = time.monotonic() + window
        while rows < self.max_batch_size:
            expired: List[ServingRequest] = []
            with self._cv:
                req, ex = self.queue.pop_locked()
                expired.extend(ex)
                if req is None and not expired:
                    wait = window_end - time.monotonic()
                    if wait <= 0 or stop.is_set():
                        break
                    self._cv.wait(timeout=wait)
                    req, ex = self.queue.pop_locked()
                    expired.extend(ex)
            for r in expired:
                on_expired(r)
            if req is None:
                if window_end - time.monotonic() <= 0 or stop.is_set():
                    break
                continue  # window re-checked at loop top
            if rows + req.n_rows > self.max_batch_size:
                self._carry = req  # never split a request across batches
                break
            if (getattr(req, "precision", None)
                    != getattr(first, "precision", None)):
                # one batch = one compiled precision variant; a
                # mismatched arrival opens the NEXT batch (same carry
                # slot as a size overflow — never dropped, never mixed)
                self._carry = req
                break
            batch.append(req)
            rows += req.n_rows
        return batch
    # hot-path note: the coalescing loop above waits only on the queue
    # CV bounded by the batch window — no device syncs, no sleeps
