"""In-process client helper over InferenceServer.

The test-and-bench-facing convenience surface: blocking single calls,
scatter/gather for many requests, and named-output dicts.  This seam is
transport-agnostic — ``paddle_tpu.serving.wire.RemoteClient`` is the
remote twin with the same signatures over an RPC hop, carrying the
trace id minted here in a W3C ``traceparent`` header.

Request-scoped tracing: every ``infer*`` call mints a trace id (or
accepts one via ``trace_id=``), propagates it through submit() into the
batcher/replica/executor span chain, and — when a flight recorder is
installed — reports the client-side span (submit -> result, the
latency the caller actually saw) so a tail-sampled record shows the
full client->device chain under one id.
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Dict, List, Optional

import numpy as np

from paddle_tpu import monitor
from paddle_tpu.monitor import flight as _flight
from paddle_tpu.monitor import spans as _spans
from paddle_tpu.serving.admission import PRIORITY_NORMAL
from paddle_tpu.serving.errors import (
    DeadlineExceeded,
    ServerClosed,
    ServingError,
)

__all__ = ["Client"]


class Client:
    def __init__(self, server):
        self._server = server
        self._fetch_names = list(server._predictor.get_output_names())

    def infer(self, feed, timeout_ms: Optional[float] = None,
              trace_id: Optional[str] = None,
              priority: int = PRIORITY_NORMAL,
              precision: Optional[str] = None) -> List[np.ndarray]:
        """Submit one request and block for its outputs (list ordered
        like the predictor's fetch list).  ``priority`` is the admission
        class (``serving.admission.PRIORITY_*``, lower = more
        important): under overload the server sheds low priority first.
        ``precision`` picks the compiled variant on a mixed-precision
        endpoint (``"fp32"`` opts this request out of the policy
        default; both are warmed, so neither choice compiles).
        ``trace_id`` joins the call to an existing trace; by default a
        fresh id is minted — read it back via ``last_trace_id``."""
        tid = trace_id or monitor.new_trace_id()
        self.last_trace_id = tid
        kw = {"precision": precision} if precision is not None else {}
        fr = _flight.get()
        rec = _spans.recording() or fr is not None
        if not rec:
            return self._server.submit(
                feed, timeout_ms=timeout_ms, trace_id=tid,
                priority=priority, **kw).result()
        t0 = time.perf_counter()
        err: Optional[BaseException] = None
        sid = _spans.new_span_id()
        try:
            with _spans.trace_context((tid,)):
                with _spans.parent_scope(sid):
                    return self._server.submit(
                        feed, timeout_ms=timeout_ms, trace_id=tid,
                        parent_span=sid, priority=priority, **kw).result()
        except BaseException as e:  # noqa: BLE001 — observed, re-raised
            err = e
            raise
        finally:
            dur = time.perf_counter() - t0
            with _spans.trace_context((tid,)):
                _spans.record_span(
                    "serving/client_infer", t0, dur, cat="client",
                    span_id=sid, error=err is not None)
            if fr is not None:
                self._flight_report(fr, tid, sid, t0, dur, err)

    @staticmethod
    def _flight_report(fr, tid: str, sid: str, t0: float, dur: float,
                       err: Optional[BaseException]) -> None:
        """Attach the client-side span to the request's tail-sampled
        record — or, for a deadline the server never got to observe
        (the future timed out client-side), create the record.  Other
        client-side errors (shed at admission, validation, server
        closed) are deliberately NOT retained: terminal server failures
        are recorded server-side, and an overload storm of rejected
        requests must not flood the bounded ring and evict the slow
        traces tail sampling exists to keep."""
        span = {
            "name": "serving/client_infer", "cat": "client", "id": sid,
            "ts": _spans.wall_ts(t0), "dur": dur,
            "tid": threading.get_ident(), "trace_ids": [tid],
        }
        if err is not None:
            span["error"] = True
        if fr.add_span(tid, span):
            return
        if err is not None and not isinstance(err, DeadlineExceeded):
            return
        fr.consider(
            tid, dur,
            "deadline" if isinstance(err, DeadlineExceeded) else "ok",
            [span])

    def infer_stream(self, feed, timeout_ms: Optional[float] = None,
                     trace_id: Optional[str] = None,
                     priority: int = PRIORITY_NORMAL,
                     max_new_tokens: Optional[int] = None):
        """Submit one decode prompt and iterate generated-token chunks
        (1-D int32 arrays) as the continuous-batching scheduler produces
        them — the first chunk arrives as soon as the request's first
        multi-step tick completes, long before the sequence finishes.

        Only a streaming endpoint (``serving.decode.DecodeServer``)
        supports this; a request-batching ``InferenceServer`` raises
        ``ServingError`` immediately.  Admission errors (shed, expired,
        closed) raise AT THIS CALL, not at first iteration; mid-stream
        failures re-raise typed from the iterator.  Abandoning the
        iterator aborts the decode so its slot frees for queued work.
        Every chunk belongs to one trace id (``last_trace_id``)."""
        if not getattr(self._server, "supports_streaming", False):
            raise ServingError(
                "endpoint does not stream (not a decode server); use "
                "infer() or serve the model with serving.decode")
        tid = trace_id or monitor.new_trace_id()
        self.last_trace_id = tid
        sid = _spans.new_span_id() if _spans.recording() else None
        kw = {}
        if max_new_tokens is not None:
            kw["max_new_tokens"] = int(max_new_tokens)
        with _spans.trace_context((tid,)):
            req = self._server.submit(
                feed, timeout_ms=timeout_ms, trace_id=tid,
                parent_span=sid, priority=priority, **kw)
        gen = self._stream_chunks(req, tid, sid)
        # a generator abandoned BEFORE its first next() never enters its
        # body, so _stream_chunks' finally can't abort the decode and
        # the slot would keep generating for a gone caller — a GC
        # finalizer covers that window (req.fail is a no-op once done,
        # so a normally-finished stream makes this inert)
        weakref.finalize(gen, Client._abort_unstarted, req)
        return gen

    @staticmethod
    def _abort_unstarted(req):
        if not req.done():
            req.fail(ServerClosed("stream consumer went away"))

    @staticmethod
    def _stream_chunks(req, tid: str, sid: Optional[str]):
        t0 = time.perf_counter()
        err: Optional[BaseException] = None
        chunks = 0
        try:
            for chunk in req.stream():
                chunks += 1
                yield chunk
        except GeneratorExit:
            raise  # abandoned: neutral, not a stream failure
        except BaseException as e:  # noqa: BLE001 — observed, re-raised
            err = e
            raise
        finally:
            if not req.done():
                if err is not None:
                    # a client-side typed failure (e.g. stream()'s own
                    # DeadlineExceeded) is the request's terminal error
                    req.fail(err)
                else:
                    # consumer walked away mid-stream: abort the decode
                    # so the slot frees for queued work at the next tick
                    req.fail(ServerClosed("stream consumer went away"))
            if sid is not None:
                with _spans.trace_context((tid,)):
                    _spans.record_span(
                        "serving/client_stream", t0,
                        time.perf_counter() - t0, cat="client",
                        span_id=sid, chunks=chunks, error=err is not None)

    def infer_named(self, feed, timeout_ms: Optional[float] = None,
                    trace_id: Optional[str] = None,
                    priority: int = PRIORITY_NORMAL) -> Dict[str, np.ndarray]:
        """infer(), but keyed by the endpoint's output names."""
        return dict(zip(self._fetch_names,
                        self.infer(feed, timeout_ms, trace_id=trace_id,
                                   priority=priority)))

    def infer_many(self, feeds, timeout_ms: Optional[float] = None,
                   priority: int = PRIORITY_NORMAL) -> List[List[np.ndarray]]:
        """Submit every feed first (so they can coalesce into shared
        batches), then gather all results in order.  Each request gets
        its own trace id (``last_trace_ids`` after the call)."""
        tids = [monitor.new_trace_id() for _ in feeds]
        futures = [
            self._server.submit(f, timeout_ms=timeout_ms, trace_id=t,
                                priority=priority)
            for f, t in zip(feeds, tids)
        ]
        self.last_trace_ids = tids
        return [f.result() for f in futures]
