"""In-process client helper over InferenceServer.

The test-and-bench-facing convenience surface: blocking single calls,
scatter/gather for many requests, and named-output dicts.  A remote
transport (RPC) would sit exactly where this class sits — everything
below (submit/future) is transport-agnostic.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["Client"]


class Client:
    def __init__(self, server):
        self._server = server
        self._fetch_names = list(server._predictor.get_output_names())

    def infer(self, feed, timeout_ms: Optional[float] = None) -> List[np.ndarray]:
        """Submit one request and block for its outputs (list ordered
        like the predictor's fetch list)."""
        return self._server.submit(feed, timeout_ms=timeout_ms).result()

    def infer_named(self, feed, timeout_ms: Optional[float] = None) -> Dict[str, np.ndarray]:
        """infer(), but keyed by the endpoint's output names."""
        return dict(zip(self._fetch_names, self.infer(feed, timeout_ms)))

    def infer_many(self, feeds, timeout_ms: Optional[float] = None) -> List[List[np.ndarray]]:
        """Submit every feed first (so they can coalesce into shared
        batches), then gather all results in order."""
        futures = [self._server.submit(f, timeout_ms=timeout_ms) for f in feeds]
        return [f.result() for f in futures]
