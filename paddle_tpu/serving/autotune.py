"""Arrival-driven bucket-ladder autotuning.

The PR-1 serving ladder is the hardcoded 1/2/4/.../max powers of two —
a shape chosen blind, before a single request arrived.  Every padded
batch pays rent for that guess: ``rung - valid`` rows computed and
sliced away.  The arrival-size histogram the metrics layer has
collected since PR 2 (``ServingMetrics.observe_arrival``) is exactly
the information needed to do better, and this module turns it into a
ladder:

* :func:`propose_ladder` — exact DP over the observed request sizes:
  choose at most ``max_rungs`` rungs (the top rung is always
  ``max_batch_size`` — ``BucketPolicy``'s contract) minimizing the
  expected padded-row waste ``sum(count[s] * (rung(s) - s))``.  With
  ``n`` distinct sizes the DP is ``O(n^2 * max_rungs)`` — trivial at
  serving batch scales.  Ties prefer FEWER rungs (each rung is one
  XLA compile per replica per precision variant).
* :func:`propose_timeout_ms` — the coalescing window from the queue's
  observed wait EWMA (``AdmissionQueue``): when requests already queue
  for W ms, a window of ~W/4 buys occupancy at marginal latency cost;
  an idle queue shrinks the window toward the floor so light traffic
  isn't taxed.
* :func:`plan` — one proposal document (ladder + timeout + the
  expected waste both ways) consumed by ``InferenceServer.
  replan_ladder`` (online, behind the warmup barrier so a ladder
  change never serves a cold cache) and by ``tools/autotune_ladder.py``
  (offline replay of a recorded histogram).
* :func:`propose_len_ladder` / :func:`plan_kv_ladder` — the SAME DP
  pointed at the decode path's KV length ladder
  (``serving/kv_pool.py``): waste counted in padded cache positions
  from the observed per-request total sequence lengths
  (``DecodeServer.seq_len_histogram``), replacing the hand-picked
  powers-of-two ``default_len_ladder``.  Offline proposal only: a
  ladder change re-warms the pool, a restart-time decision.

Everything here is pure host-side arithmetic on snapshots — it runs on
the autotuner's own thread (or offline), never inside the dispatch hot
path (``tools/check_hot_path.py`` keeps this file on its checked list
so a future region added here is guarded).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "expected_waste",
    "propose_ladder",
    "propose_len_ladder",
    "plan_kv_ladder",
    "propose_id_bucket_ladder",
    "plan_id_ladder",
    "propose_timeout_ms",
    "plan",
]


def _normalize_counts(counts, max_batch_size: int) -> Dict[int, int]:
    out: Dict[int, int] = {}
    for k, v in dict(counts or {}).items():
        k, v = int(k), int(v)
        if v > 0 and 0 < k <= int(max_batch_size):
            out[k] = out.get(k, 0) + v
    return out


def expected_waste(counts, ladder: Sequence[int],
                   max_batch_size: Optional[int] = None
                   ) -> Tuple[int, int]:
    """``(waste_rows, padded_rows)`` the ``ladder`` would pay serving
    one request per histogram entry (requests-as-batches: the
    occupancy-neutral comparison both the tests and the offline tool
    use — coalescing shifts both ladders equally).

    Sizes ABOVE the ladder's top rung are excluded from both totals:
    this ladder cannot serve them at all (``BucketPolicy.bucket_for``
    rejects them), so crediting them with a rung would fabricate
    negative waste and poison the comparison a re-plan is judged by."""
    ladder = sorted(int(b) for b in ladder)
    counts = _normalize_counts(
        counts, min(int(max_batch_size), ladder[-1])
        if max_batch_size is not None else ladder[-1])
    waste = padded = 0
    for size, n in counts.items():
        rung = next(r for r in ladder if r >= size)
        waste += (rung - size) * n
        padded += rung * n
    return waste, padded


def propose_ladder(counts, max_batch_size: int,
                   max_rungs: int = 8) -> Optional[List[int]]:
    """The waste-minimal ladder for an observed arrival histogram, or
    None when the histogram is empty (nothing to plan from — keep the
    current ladder)."""
    M = int(max_batch_size)
    if M < 1:
        raise ValueError("max_batch_size must be >= 1, got %r" % M)
    counts = _normalize_counts(counts, M)
    if not counts:
        return None
    cand = sorted(set(counts) | {M})
    ncand = len(cand)
    k_max = max(1, min(int(max_rungs), ncand))
    # hot-path: begin ladder_plan (pure host arithmetic on a histogram
    # snapshot; the server holds its replan lock while this runs, so a
    # device sync or sleep here would stall every concurrent replan)

    def seg_cost(lo: int, hi: int) -> int:
        # waste of serving every size s with lo < s <= hi at rung hi
        return sum((hi - s) * n for s, n in counts.items() if lo < s <= hi)

    INF = float("inf")
    # dp[k][j]: minimal waste covering all sizes <= cand[j] with k
    # rungs, the largest being cand[j]
    dp = [[INF] * ncand for _ in range(k_max + 1)]
    parent: List[List[Optional[int]]] = [
        [None] * ncand for _ in range(k_max + 1)]
    for j in range(ncand):
        dp[1][j] = seg_cost(0, cand[j])
    for k in range(2, k_max + 1):
        for j in range(ncand):
            for i in range(j):
                if dp[k - 1][i] is INF:
                    continue
                c = dp[k - 1][i] + seg_cost(cand[i], cand[j])
                if c < dp[k][j]:
                    dp[k][j] = c
                    parent[k][j] = i
    top = ncand - 1  # the ladder must top out at max_batch_size
    best_k = 1
    for k in range(2, k_max + 1):
        if dp[k][top] < dp[best_k][top]:  # strict: ties keep fewer rungs
            best_k = k
    ladder = []
    k, j = best_k, top
    while j is not None:
        ladder.append(cand[j])
        j = parent[k][j]
        k -= 1
    ladder = sorted(set(ladder))
    # the reconstruction starts at the M candidate, so the ladder tops
    # out at max_batch_size by construction (BucketPolicy's contract)
    assert ladder[-1] == M
    # hot-path: end ladder_plan
    return ladder


def propose_len_ladder(seq_len_counts, max_seq_len: int,
                       max_rungs: int = 6) -> Optional[List[int]]:
    """The waste-minimal KV length-bucket ladder for an observed
    sequence-length histogram (``DecodeServer`` records total sequence
    length — prompt + generation budget — per admitted request as
    ``seq_len_histogram``), or None when the histogram is empty.

    Same exact DP as :func:`propose_ladder`, with waste counted in
    padded CACHE POSITIONS instead of batch rows: a sequence of total
    length ``s`` decoded on length rung ``r`` carries ``r - s`` dead
    cache slots for its whole lifetime, in HBM and in every attention
    step.  The result drops into ``KVSlotPool(len_ladder=...)`` /
    ``DecodeServer(len_ladder=...)``; each rung is one AOT compile per
    slot rung at warmup, so ties prefer fewer rungs exactly like the
    batch ladder.  Offline proposal only — replacing a live pool's
    ladder means re-warming, which is a restart-time decision."""
    return propose_ladder(seq_len_counts, max_seq_len,
                          max_rungs=max_rungs)


def plan_kv_ladder(seq_len_histogram, max_seq_len: int,
                   current_ladder: Optional[Sequence[int]] = None,
                   max_rungs: int = 6) -> Dict[str, object]:
    """One KV-ladder proposal document: the waste-minimal length ladder
    for the observed sequence lengths vs the current (default:
    ``kv_pool.default_len_ladder`` — the hand-picked powers of two),
    with expected padded-position waste both ways so the improvement is
    a number, not a claim."""
    from paddle_tpu.serving.kv_pool import default_len_ladder

    current = sorted(int(b) for b in (
        current_ladder if current_ladder is not None
        else default_len_ladder(int(max_seq_len))))
    proposed = propose_len_ladder(seq_len_histogram, max_seq_len,
                                  max_rungs=max_rungs)
    if proposed is None:
        proposed = list(current)
    cur_w, cur_p = expected_waste(seq_len_histogram, current, max_seq_len)
    new_w, new_p = expected_waste(seq_len_histogram, proposed, max_seq_len)
    return {
        "len_ladder": proposed,
        "changed": proposed != current,
        "current_waste_ratio": round(cur_w / cur_p, 6) if cur_p else None,
        "proposed_waste_ratio": round(new_w / new_p, 6) if new_p else None,
        "waste_positions_saved": int(cur_w - new_w),
        "n_lengths_observed": len(
            _normalize_counts(seq_len_histogram, max_seq_len)),
    }


def propose_id_bucket_ladder(uniq_id_counts, max_unique: int,
                             max_rungs: int = 8) -> Optional[List[int]]:
    """The waste-minimal UNIQUE-ID bucket ladder for an observed
    per-batch unique-id-count histogram (the executor's sparse
    prefetch records ``len(unique(batch ids))`` per table per batch as
    ``program._uniq_id_hist``), or None when the histogram is empty.

    Same exact DP as :func:`propose_ladder`, with waste counted in
    padded ID SLOTS: a batch with ``n`` unique ids bucketed to rung
    ``r`` pulls (PS path) or gathers + pushes (mesh path) ``r - n``
    padding rows per table per step.  The result replaces the
    hardcoded power-of-two buckets via
    ``bind_distributed_tables(..., id_bucket_ladder=...)`` /
    ``program._sparse_id_ladder``.  Offline proposal only: each rung
    is one compiled lookup/push shape, so changing a live ladder means
    re-warming — a restart-time decision, exactly like the KV length
    ladder."""
    return propose_ladder(uniq_id_counts, max_unique, max_rungs=max_rungs)


def _pow2_id_ladder(max_unique: int) -> List[int]:
    """The executor's default unique-count buckets: 8, 16, ... up to
    the next power of two covering ``max_unique`` (the bucket rounding
    is the executor's own ``pow2_id_bucket`` — one definition, so this
    comparison baseline can never drift from the runtime)."""
    from paddle_tpu.executor import pow2_id_bucket

    ladder, b = [], 8
    top = pow2_id_bucket(int(max_unique))
    while b < top:
        ladder.append(b)
        b *= 2
    ladder.append(top)
    return ladder


def plan_id_ladder(uniq_id_histogram,
                   max_unique: Optional[int] = None,
                   current_ladder: Optional[Sequence[int]] = None,
                   max_rungs: int = 8) -> Dict[str, object]:
    """One id-ladder proposal document: the waste-minimal unique-id
    bucket ladder for the observed histogram vs the current (default:
    the executor's power-of-two buckets), with the expected padded-slot
    waste both ways.  ``max_unique`` defaults to the largest observed
    count (the histogram IS the traffic)."""
    counts = {int(k): int(v) for k, v in dict(uniq_id_histogram or {}).items()
              if int(v) > 0 and int(k) > 0}
    if max_unique is None:
        if not counts:
            raise ValueError(
                "empty unique-id histogram and no max_unique given — "
                "nothing to plan from")
        max_unique = max(counts)
    current = sorted(int(b) for b in (
        current_ladder if current_ladder is not None
        else _pow2_id_ladder(int(max_unique))))
    proposed = propose_id_bucket_ladder(counts, int(max_unique),
                                        max_rungs=max_rungs)
    if proposed is None:
        proposed = list(current)
    # compare over the shared coverage: the pow2 default always tops
    # out at >= max_unique, so both ladders serve every observed size
    cur_w, cur_p = expected_waste(counts, current, current[-1])
    new_w, new_p = expected_waste(counts, proposed, current[-1])
    return {
        "id_ladder": proposed,
        "changed": proposed != current,
        "current_waste_ratio": round(cur_w / cur_p, 6) if cur_p else None,
        "proposed_waste_ratio": round(new_w / new_p, 6) if new_p else None,
        "waste_slots_saved": int(cur_w - new_w),
        "n_counts_observed": len(counts),
    }


def propose_timeout_ms(queue_wait_ewma_ms: Optional[float],
                       current_ms: Optional[float] = None,
                       min_ms: float = 0.5, max_ms: float = 50.0) -> float:
    """Coalescing window from the observed queue wait: ~W/4, clamped.
    With no signal yet, keep the current window (or the floor)."""
    if not queue_wait_ewma_ms or queue_wait_ewma_ms <= 0:
        return float(current_ms) if current_ms else float(min_ms)
    return round(min(float(max_ms),
                     max(float(min_ms), 0.25 * float(queue_wait_ewma_ms))),
                 3)


def plan(arrival_histogram, max_batch_size: int,
         current_ladder: Sequence[int],
         queue_wait_ewma_ms: Optional[float] = None,
         current_timeout_ms: Optional[float] = None,
         max_rungs: int = 8) -> Dict[str, object]:
    """One autotune proposal: the waste-minimal ladder for the observed
    arrivals plus a queue-wait-derived batch window, with the expected
    waste of both ladders so the improvement is a number, not a claim."""
    current_ladder = sorted(int(b) for b in current_ladder)
    proposed = propose_ladder(arrival_histogram, max_batch_size,
                              max_rungs=max_rungs)
    if proposed is None:
        proposed = list(current_ladder)
    cur_w, cur_p = expected_waste(
        arrival_histogram, current_ladder, max_batch_size)
    new_w, new_p = expected_waste(
        arrival_histogram, proposed, max_batch_size)
    return {
        "ladder": proposed,
        "changed": proposed != current_ladder,
        "batch_timeout_ms": propose_timeout_ms(
            queue_wait_ewma_ms, current_timeout_ms),
        "current_waste_ratio": round(cur_w / cur_p, 6) if cur_p else None,
        "proposed_waste_ratio": round(new_w / new_p, 6) if new_p else None,
        "waste_rows_saved": int(cur_w - new_w),
        "n_sizes_observed": len(
            _normalize_counts(arrival_histogram, max_batch_size)),
    }
