"""Typed serving errors.

The admission-control / deadline / lifecycle contract is error-typed so
callers can distinguish "retry later" (ServerOverloaded), "client gave
up" (DeadlineExceeded), and "stop sending" (ServerClosed) without
string-matching — the Clipper/Orca-style front-end contract the
reference stack leaves to the external serving system.
"""
from __future__ import annotations

__all__ = [
    "ServingError",
    "ServerOverloaded",
    "DeadlineExceeded",
    "ServerClosed",
    "WireProtocolError",
    "BackendUnavailable",
    "RelaunchFailed",
]


class ServingError(RuntimeError):
    """Base class for all serving-layer errors."""


class ServerOverloaded(ServingError):
    """Admission control shed this request: the bounded request queue is
    at its (adaptive) limit and no lower-priority entry could be evicted
    to make room, or the brownout ladder is shedding this priority
    class.  The request was NOT enqueued (or was evicted before any
    work ran); back off and retry.

    ``retry_after_ms`` is the server's computed backoff hint (EWMA queue
    wait scaled by the overload ratio).  It rides the wire as response
    meta (and an HTTP ``Retry-After`` header), and the fleet balancer's
    retry pacing honors it — a shedding backend is not re-dispatched to
    before the hint elapses."""

    def __init__(self, message: str = "server overloaded",
                 retry_after_ms: "float | None" = None):
        super().__init__(message)
        self.retry_after_ms = (
            float(retry_after_ms) if retry_after_ms is not None else None)


class DeadlineExceeded(ServingError, TimeoutError):
    """The request's deadline expired before a result was produced —
    either while queued (the server sheds it instead of running stale
    work) or while the client waited on the future."""


class ServerClosed(ServingError):
    """The server is shutting down (or already stopped) and no longer
    admits new requests."""


class WireProtocolError(ServingError):
    """A wire message violated the framing/codec contract (bad magic,
    truncated frame, oversized frame, unknown frame kind, undecodable
    payload).  Raised by the codec's BOUNDED reads, so a malformed or
    malicious peer surfaces as a typed per-request failure instead of
    wedging a server process on an unbounded read."""


class BackendUnavailable(ServingError):
    """The wire transport could not complete the exchange with the
    remote process (connection refused/reset, half-written response —
    the process died or the network dropped).  The RETRYABLE failure
    class: the front-end balancer re-routes the request to a surviving
    backend, exactly as the in-process fleet requeues a batch off a dead
    replica thread."""


class RelaunchFailed(ServingError):
    """The supervisor gave up reviving a crash-looping serving child:
    every relaunch attempt inside its capped-backoff budget failed.  The
    backend stays retired; an operator (or a replacement launch) has to
    intervene — the supervisor will not relaunch-storm a child that
    cannot come up."""
