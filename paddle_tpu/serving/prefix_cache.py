"""Prefix KV cache: block-hashed prompt-prefix reuse for decode.

The few-system-prompts-many-users traffic shape re-prefills the same
prompt head thousands of times — the dominant decode-server cost after
the per-token step itself.  :class:`PrefixKVCache` retains FINISHED
slots' KV blocks (the vLLM lineage: Kwon et al., SOSP 2023, at block
granularity rather than per-page) in a bounded byte-budget LRU, keyed
by a hash of the prompt-token prefix at ``block_tokens`` boundaries:

* **Offer** — when the scheduler frees a slot, the prompt's longest
  block-aligned prefix (bounded by the positions the slot actually
  consumed) is hashed and its KV rows extracted (one host materialize
  per retained entry — a control-plane move off the tick's hot path,
  like a rung transition).
* **Probe** — at admission, the incoming prompt is hashed at descending
  block boundaries; the longest match hands back retained KV leaves and
  the admit executable installs them, so prefill drops to the unmatched
  suffix (the prefill-token counter is the ground truth the tests and
  bench assert on).  Hash collisions cannot serve wrong tokens: every
  entry stores its prefix tokens and a probe compares them exactly.
* **Invalidation** — an endpoint reload (new weights) calls
  :meth:`invalidate`; retained KV from old weights must never seed new
  decodes.

The cache is prompt-token keyed and position-absolute, so an entry is
valid for ANY later prompt sharing the prefix — the write-before-read
pool invariant covers the suffix positions, exactly as it covers slot
reuse.  Metrics: ``serving_prefix_cache_{hits,misses,evictions}_total``
counters and the ``serving_prefix_cache_bytes`` gauge, labeled by cache
name and retired by :meth:`close`.
"""
from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from paddle_tpu import monitor

__all__ = ["PrefixKVCache"]

_LABELS = ("cache",)
PREFIX_HITS = monitor.counter(
    "serving_prefix_cache_hits_total",
    "decode admissions that matched a retained prompt-prefix and "
    "skipped its prefill (shared-prefix KV reuse)", _LABELS)
PREFIX_MISSES = monitor.counter(
    "serving_prefix_cache_misses_total",
    "decode admissions probed against the prefix KV cache with no "
    "block-aligned match (full prefill)", _LABELS)
PREFIX_EVICTIONS = monitor.counter(
    "serving_prefix_cache_evictions_total",
    "prefix KV entries evicted by the byte-budget LRU", _LABELS)
PREFIX_BYTES = monitor.gauge(
    "serving_prefix_cache_bytes",
    "bytes of retained prefix KV blocks (tokens + cache leaves) "
    "currently held by the prefix cache", _LABELS)


class PrefixKVCache:
    """Bounded LRU of prompt-prefix KV blocks for one decode endpoint.

    ``capacity_bytes`` bounds the sum of retained entry sizes (prefix
    tokens + extracted KV leaves); ``block_tokens`` is the hash
    granularity — prefixes are keyed only at multiples of it, so two
    prompts share an entry iff they agree on whole blocks.  One cache
    serves ONE endpoint (one weight set / pool layout); entries are not
    portable across servers.
    """

    def __init__(self, capacity_bytes: int = 64 << 20,
                 block_tokens: int = 16, name: str = "prefix"):
        if int(capacity_bytes) < 1:
            raise ValueError(
                "capacity_bytes must be >= 1, got %r" % capacity_bytes)
        if int(block_tokens) < 1:
            raise ValueError(
                "block_tokens must be >= 1, got %r" % block_tokens)
        self.capacity_bytes = int(capacity_bytes)
        self.block_tokens = int(block_tokens)
        self.name = name
        # key -> {"tokens": [m] int32, "leaves": [np arrays | None],
        #         "nbytes": int}
        self._data: "OrderedDict[str, Dict[str, object]]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        self._fallbacks = 0
        lbl = {"cache": name}
        self._c_hits = PREFIX_HITS.labels(**lbl)
        self._c_misses = PREFIX_MISSES.labels(**lbl)
        self._c_evictions = PREFIX_EVICTIONS.labels(**lbl)
        self._g_bytes = PREFIX_BYTES.labels(**lbl)

    # ------------------------------------------------------------------
    def __len__(self) -> int:
        return len(self._data)

    @staticmethod
    def _hash(tokens: np.ndarray) -> str:
        return hashlib.sha1(
            np.ascontiguousarray(tokens, np.int32).tobytes()).hexdigest()

    # ------------------------------------------------------------------
    # hot-path: begin prefix_probe (hash + dict probes under the cache
    # lock, on the scheduler thread between ticks — pure host work, no
    # device syncs, no sleeps; the KV install itself is one warmed
    # admit_prefix dispatch)
    def probe(self, prompt) -> Tuple[int, Optional[List[np.ndarray]]]:
        """Longest retained block-aligned proper prefix of ``prompt``:
        ``(prefix_len, kv_leaves)``, or ``(0, None)`` on a miss.  The
        match is capped one token short of the prompt so the suffix
        always re-enters prefill (the step consuming the LAST prompt
        token produces the first generated one — it must run).  Stored
        tokens are compared exactly, so a hash collision can never
        install another prompt's KV."""
        B = self.block_tokens
        m = ((len(prompt) - 1) // B) * B
        if m <= 0:
            self._count_miss()
            return 0, None
        with self._lock:
            while m > 0:
                key = self._hash(prompt[:m])
                ent = self._data.get(key)
                if ent is not None and np.array_equal(
                        ent["tokens"], prompt[:m]):
                    self._data.move_to_end(key)
                    self._hits += 1
                    self._c_hits.inc()
                    return m, list(ent["leaves"])
                m -= B
            self._misses += 1
            self._c_misses.inc()
        return 0, None
    # hot-path: end prefix_probe

    def _count_miss(self) -> None:
        with self._lock:
            self._misses += 1
        self._c_misses.inc()

    def count_fallback(self) -> None:
        """A prefix admission that fell back to full prefill (fault
        injection / corrupted entry) — tracked for :meth:`stats`; the
        server's own metrics count it as ``prefix_fallback``."""
        with self._lock:
            self._fallbacks += 1

    # ------------------------------------------------------------------
    def offer(self, prompt, consumed: int,
              extract: Callable[[int], List[Optional[np.ndarray]]]) -> bool:
        """Retain a freed slot's prefix KV: hash the prompt's longest
        block-aligned prefix covered by the slot's ``consumed``
        positions and store ``extract(m)`` (the pool's KV leaves for
        positions ``< m``).  Returns True when a new entry was stored.
        The extract (a host materialize) runs OUTSIDE the cache lock and
        only for new keys — repeat offers of a hot prefix are one dict
        probe."""
        B = self.block_tokens
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        m = (len(prompt) // B) * B
        m = min(m, (int(consumed) // B) * B)
        if m <= 0:
            return False
        key = self._hash(prompt[:m])
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
                return False
        leaves = extract(m)
        tokens = prompt[:m].copy()
        nbytes = int(tokens.nbytes) + sum(
            int(leaf.nbytes) for leaf in leaves if leaf is not None)
        with self._lock:
            if key in self._data:  # lost the race to a concurrent offer
                self._data.move_to_end(key)
                return False
            self._data[key] = {
                "tokens": tokens, "leaves": leaves, "nbytes": nbytes}
            self._bytes += nbytes
            evicted = 0
            while self._bytes > self.capacity_bytes and self._data:
                _, ev = self._data.popitem(last=False)
                self._bytes -= int(ev["nbytes"])
                evicted += 1
            if evicted:
                self._evictions += evicted
                self._c_evictions.inc(evicted)
            self._g_bytes.set(float(self._bytes))
        return True

    # ------------------------------------------------------------------
    def invalidate(self) -> None:
        """Drop every entry — the endpoint-reload path: retained KV from
        the previous weights must never seed a new decode."""
        with self._lock:
            self._data.clear()
            self._bytes = 0
            self._g_bytes.set(0.0)

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, object]:
        with self._lock:
            total = self._hits + self._misses
            return {
                "entries": len(self._data),
                "bytes": self._bytes,
                "capacity_bytes": self.capacity_bytes,
                "block_tokens": self.block_tokens,
                "hits": self._hits,
                "misses": self._misses,
                "evictions": self._evictions,
                "fallbacks": self._fallbacks,
                "hit_ratio": (round(self._hits / total, 6)
                              if total else None),
            }

    def close(self) -> None:
        """Retire this cache's series from the exposition."""
        lbl = {"cache": self.name}
        for metric in (PREFIX_HITS, PREFIX_MISSES, PREFIX_EVICTIONS,
                       PREFIX_BYTES):
            metric.remove_labels(**lbl)
        with self._lock:
            self._data.clear()
            self._bytes = 0
