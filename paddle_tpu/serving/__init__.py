"""paddle_tpu.serving — dynamic-batching TPU inference serving.

An Orca/Clipper-style in-process serving front end over
AnalysisPredictor (inference.py):

* ``InferenceServer`` — bounded request queue, DynamicBatcher
  coalescing (max_batch_size / batch_timeout_ms), bucket-ladder batch
  padding so the jit cache sees a closed shape set, per-request
  deadlines, overload shedding, graceful drain;
* ``Client`` — blocking in-process client helper; mints a per-request
  trace id (Dapper-style) that propagates through the batcher, replica
  worker, and executor span chain, so a ``monitor.trace_session`` or
  ``monitor.flight_recorder`` attributes every span to its requests;
* ``BucketPolicy`` / ``DynamicBatcher`` / ``ServingMetrics`` — the
  composable pieces (metrics delegate to the process-global
  ``paddle_tpu.monitor`` registry, labeled ``server=<name>``; the
  request-latency histogram carries ``trace_id`` exemplars);
* ``server.start_admin()`` — localhost HTTP ``/metrics`` (Prometheus
  text exposition; OpenMetrics 1.0 with exemplars via Accept) +
  ``/statusz`` (JSON snapshot) + ``/tracez`` (tail-sampled
  slow/errored request traces) surface;
* typed errors: ``ServerOverloaded``, ``DeadlineExceeded``,
  ``ServerClosed`` (+ the wire layer's ``WireProtocolError`` /
  ``BackendUnavailable``);
* ``serving.wire`` (lazy subpackage) — the cross-host tier: codec +
  HTTP transport, ``RemoteClient``, ``ServingProcess`` children, and
  the ``FleetBalancer`` front end;
* ``serving.decode`` (lazy module) — continuous-batching token-level
  decode: ``DecodeServer`` over the bucketed KV-cache slot pool
  (``serving.kv_pool``), streamed to clients via ``infer_stream``.

Quickstart::

    pred = create_paddle_predictor(AnalysisConfig(model_dir))
    server = serving.InferenceServer(pred, max_batch_size=16)
    server.warmup()            # pre-compile every bucket; arms the
                               # zero-recompile counter
    out, = serving.Client(server).infer({"x": rows})
    server.stop(drain=True)
"""
from paddle_tpu.serving.admission import (
    PRIORITY_HIGH,
    PRIORITY_LOW,
    PRIORITY_NORMAL,
    AdmissionQueue,
    BrownoutController,
)
from paddle_tpu.serving.batching import DynamicBatcher, ServingRequest
from paddle_tpu.serving.bucketing import BucketPolicy
from paddle_tpu.serving.client import Client
from paddle_tpu.serving.errors import (
    BackendUnavailable,
    DeadlineExceeded,
    ServerClosed,
    ServerOverloaded,
    ServingError,
    WireProtocolError,
)
from paddle_tpu.serving.embedding_cache import EmbeddingRowCache
from paddle_tpu.serving.metrics import ServingMetrics
from paddle_tpu.serving.server import InferenceServer

__all__ = [
    "InferenceServer",
    "Client",
    "DynamicBatcher",
    "ServingRequest",
    "BucketPolicy",
    "AdmissionQueue",
    "BrownoutController",
    "PRIORITY_HIGH",
    "PRIORITY_NORMAL",
    "PRIORITY_LOW",
    "ServingMetrics",
    "EmbeddingRowCache",
    "ServingError",
    "ServerOverloaded",
    "DeadlineExceeded",
    "ServerClosed",
    "WireProtocolError",
    "BackendUnavailable",
    "wire",
    "decode",
]


def __getattr__(name):
    # the wire subpackage and the decode module are imported lazily:
    # the in-process serving path must not pay the transport/launcher
    # import (and its metric registrations) — or the decode scheduler's
    # — unless the process actually uses them
    if name in ("wire", "decode"):
        import importlib

        mod = importlib.import_module("paddle_tpu.serving." + name)
        globals()[name] = mod
        return mod
    raise AttributeError("module %r has no attribute %r" % (__name__, name))
