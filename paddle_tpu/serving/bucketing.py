"""Batch-dim shape bucketing.

The predictor jit-caches per feed signature (executor.py cache key), so
every novel batch size is an XLA recompile — fatal for a serving tail
where request counts are arbitrary.  The fix is the standard one: pad
the coalesced batch up to a fixed ladder of sizes (1/2/4/.../max by
default) so the compiled-shape set is CLOSED and finite; ``warmup()``
pre-compiles every rung, after which steady-state serving never
compiles again (asserted via Executor.jit_cache_stats).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["BucketPolicy"]


class BucketPolicy:
    """Pads the batch dim up to a fixed ladder of sizes.

    ``ladder`` defaults to the powers of two up to ``max_batch_size``,
    with ``max_batch_size`` itself appended when it is not a power of
    two — e.g. max 12 -> (1, 2, 4, 8, 12).
    """

    def __init__(self, max_batch_size: int, ladder: Optional[Sequence[int]] = None):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1, got %r" % max_batch_size)
        if ladder is None:
            ladder = []
            b = 1
            while b < max_batch_size:
                ladder.append(b)
                b *= 2
            ladder.append(max_batch_size)
        ladder = sorted(set(int(b) for b in ladder))
        if not ladder or ladder[0] < 1:
            raise ValueError("bucket ladder must be positive, got %r" % (ladder,))
        if ladder[-1] != max_batch_size:
            raise ValueError(
                "bucket ladder %r must top out at max_batch_size=%d"
                % (ladder, max_batch_size))
        self.max_batch_size = int(max_batch_size)
        self.ladder: List[int] = ladder

    def bucket_for(self, n: int) -> int:
        """Smallest ladder rung >= n."""
        if not 0 < n <= self.max_batch_size:
            raise ValueError(
                "batch of %d rows does not fit the ladder (max %d)"
                % (n, self.max_batch_size))
        for b in self.ladder:
            if b >= n:
                return b
        raise AssertionError("unreachable: ladder tops at max_batch_size")

    def pad_feed(self, feed: Dict[str, np.ndarray], bucket: int) -> Dict[str, np.ndarray]:
        """Pad every feed array's leading dim up to ``bucket`` by
        repeating the last real row — a REAL row, so padding can never
        introduce out-of-range values (e.g. embedding ids) that a
        zeros-pad could; padded rows are computed and discarded
        (AnalysisPredictor.run_padded slices them off)."""
        out = {}
        for name, arr in feed.items():
            arr = np.asarray(arr)
            n = arr.shape[0]
            if n > bucket:
                raise ValueError(
                    "feed %r has %d rows > bucket %d" % (name, n, bucket))
            if n < bucket:
                pad = np.repeat(arr[-1:], bucket - n, axis=0)
                arr = np.concatenate([arr, pad], axis=0)
            out[name] = arr
        return out
