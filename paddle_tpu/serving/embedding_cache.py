"""Hot-id embedding row cache: the serving tier in front of PS lookups.

CTR traffic is Zipf-skewed — a tiny fraction of ids absorbs most
lookups — so a bounded row cache in front of the parameter server turns
most of serving's per-batch PS round-trips into host-memory probes, and
under pressure (or a PS outage) becomes the availability floor: the
brownout ladder's cache-only rung serves hits from the cache and misses
from a fallback row (the running mean of pulled rows, or zeros) instead
of queuing on an unreachable PS.

* **Bounded LRU** keyed ``(table, id)`` with row-count capacity —
  eviction is strict LRU (`OrderedDict` move-to-end on hit).
* **Read-through** — :meth:`lookup_through` probes the cache, pulls
  only the missing ids from the PS client, inserts, and returns the
  assembled ``[len(ids), dim]`` rows.  Hit/miss accounting is
  OCCURRENCE-weighted (the caller passes each unique id's occurrence
  count): the hit ratio is the fraction of *served rows* that never
  touched the PS, which is the number capacity planning needs.
* **Cache-only mode** (the brownout rung): misses serve the fallback
  row and count ``serving_embedding_cache_fallback_rows_total`` — no
  PS touch at all, so Zipf traffic degrades gracefully instead of
  queuing.  Outside cache-only mode a PS failure propagates typed to
  the caller (a request fully covered by cached rows still succeeds —
  the outage-survival property the chaos suite pins).
* **Invalidation** — a sparse-grad push changes rows server-side, and
  a checkpoint restore rewrites them wholesale; both paths invalidate
  (``executor.run``'s push site calls :meth:`invalidate_ids`,
  ``TrainCheckpoint.restore`` calls :meth:`invalidate`) so a cached
  copy can never be served stale.

Metrics: ``serving_embedding_cache_{hits,misses}_total``,
``serving_embedding_cache_fallback_rows_total`` and the
``serving_embedding_cache_hit_ratio`` gauge, labeled by cache name and
retired by :meth:`close`.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np

from paddle_tpu import monitor

__all__ = ["EmbeddingRowCache"]

_LABELS = ("cache",)
CACHE_HITS = monitor.counter(
    "serving_embedding_cache_hits_total",
    "embedding rows served from the hot-id cache (occurrence-weighted: "
    "each served row counts, not each unique id)", _LABELS)
CACHE_MISSES = monitor.counter(
    "serving_embedding_cache_misses_total",
    "embedding rows that missed the hot-id cache (pulled from the PS, "
    "or served from the fallback under cache-only brownout)", _LABELS)
CACHE_FALLBACK = monitor.counter(
    "serving_embedding_cache_fallback_rows_total",
    "missed rows served from the fallback (mean/zero) row because "
    "cache-only mode was active (the brownout rung / a PS outage)",
    _LABELS)
CACHE_HIT_RATIO = monitor.gauge(
    "serving_embedding_cache_hit_ratio",
    "cumulative hits / (hits + misses) for the hot-id embedding cache "
    "(the Zipf-absorption number the cache is sized by)", _LABELS)


class EmbeddingRowCache:
    """Bounded LRU of embedding rows keyed ``(table, id)``.

    ``capacity_rows`` bounds TOTAL rows across all tables (the
    operational budget is host memory, not per-table fairness).
    ``fallback``: what a cache-only miss serves — ``"mean"`` (running
    mean of every row inserted for that table; a fresh table falls
    back to zeros) or ``"zero"``.
    """

    def __init__(self, capacity_rows: int, name: str = "cache",
                 fallback: str = "mean"):
        if int(capacity_rows) < 1:
            raise ValueError(
                "capacity_rows must be >= 1, got %r" % capacity_rows)
        if fallback not in ("mean", "zero"):
            raise ValueError(
                "fallback must be 'mean' or 'zero', got %r" % fallback)
        self.capacity_rows = int(capacity_rows)
        self.name = name
        self.fallback = fallback
        self._data: "OrderedDict[Tuple[str, int], np.ndarray]" = OrderedDict()
        self._lock = threading.Lock()
        self._cache_only = False
        # per-table running sum/count of INSERTED rows: the mean-row
        # fallback estimator (not decremented on evict — an estimator
        # of the table's row distribution, not of current contents)
        self._mean: Dict[str, list] = {}
        self._hits = 0
        self._misses = 0
        self._fallback_rows = 0
        self._pulled_rows = 0  # unique rows actually fetched from the PS
        lbl = {"cache": name}
        self._c_hits = CACHE_HITS.labels(**lbl)
        self._c_misses = CACHE_MISSES.labels(**lbl)
        self._c_fallback = CACHE_FALLBACK.labels(**lbl)
        self._g_ratio = CACHE_HIT_RATIO.labels(**lbl)

    # ------------------------------------------------------------------
    @property
    def cache_only(self) -> bool:
        return self._cache_only

    def set_cache_only(self, on: bool) -> None:
        """Flip the brownout cache-only mode.  Idempotent and cheap (a
        bool store); the transition emits an instant marker so the
        degradation window is visible on the timeline."""
        on = bool(on)
        if on == self._cache_only:
            return
        self._cache_only = on
        monitor.record_instant(
            "serving/embedding_cache_only", cat="serving",
            cache=self.name, on=on)

    # ------------------------------------------------------------------
    def __len__(self) -> int:
        return len(self._data)

    def _observe(self, hits: int, misses: int) -> None:
        self._hits += hits
        self._misses += misses
        if hits:
            self._c_hits.inc(hits)
        if misses:
            self._c_misses.inc(misses)
        total = self._hits + self._misses
        if total:
            self._g_ratio.set(round(self._hits / total, 6))

    def hit_ratio(self) -> Optional[float]:
        with self._lock:
            total = self._hits + self._misses
            return (self._hits / total) if total else None

    # ------------------------------------------------------------------
    # hot-path: begin cache_probe (dict probes + row copies under the
    # cache lock; the PS pull for misses happens OUTSIDE the lock and
    # outside this region's claim — no sleeps, no device syncs)
    def lookup_through(self, client, table: str, ids,
                       n_valid: Optional[int] = None,
                       counts=None) -> np.ndarray:
        """Rows for ``ids`` (``[len(ids), dim]`` float32), cache-aside.

        ``n_valid``: the real unique count — entries past it are the
        bucket padding (repeats of ids[0]) and are excluded from the
        hit/miss accounting.  ``counts``: per-unique occurrence counts
        (occurrence-weighted accounting; defaults to 1 each).  Misses
        pull from ``client`` and populate the cache; in cache-only mode
        they serve the fallback row instead (counted).  ``client`` may
        be None only in cache-only mode."""
        ids = np.asarray(ids).reshape(-1).astype(np.int64)  # hot-ok: host id array, not a device sync
        n = len(ids) if n_valid is None else int(n_valid)
        if n <= 0:  # degenerate all-padding bucket: probe everything,
            n = len(ids)  # unweighted (counts only covers real ids)
            counts = None
        rows = None
        missing: list = []
        hit_w = miss_w = 0
        with self._lock:
            data = self._data
            for i in range(n):
                key = (table, int(ids[i]))
                row = data.get(key)
                w = int(counts[i]) if counts is not None else 1
                if row is None:
                    missing.append(i)
                    miss_w += w
                else:
                    data.move_to_end(key)
                    if rows is None:
                        rows = np.empty((len(ids), len(row)), np.float32)
                    rows[i] = row
                    hit_w += w
            self._observe(hit_w, miss_w)
    # hot-path: end cache_probe
        if missing:
            if self._cache_only:
                fb_dim = rows.shape[1] if rows is not None else None
                fb = self._fallback_row(table, fb_dim)
                if fb is None:
                    # nothing cached for this table yet and no known
                    # dim: the PS (if reachable) is the only source
                    if client is None:
                        raise RuntimeError(
                            "embedding cache %r: cache-only mode with "
                            "no cached rows for table %r and no row "
                            "dim known" % (self.name, table))
                    fb_rows = client.pull_sparse(
                        table, ids[missing])
                    if rows is None:
                        rows = np.empty(
                            (len(ids), fb_rows.shape[1]), np.float32)
                    for k, i in enumerate(missing):
                        rows[i] = fb_rows[k]
                else:
                    if rows is None:
                        rows = np.empty((len(ids), len(fb)), np.float32)
                    for i in missing:
                        rows[i] = fb
                    with self._lock:
                        self._fallback_rows += len(missing)
                    self._c_fallback.inc(len(missing))
            else:
                if client is None:
                    raise RuntimeError(
                        "embedding cache %r: %d missed row(s) for "
                        "table %r and no PS client to pull from"
                        % (self.name, len(missing), table))
                pulled = client.pull_sparse(table, ids[missing])
                if rows is None:
                    rows = np.empty((len(ids), pulled.shape[1]), np.float32)
                for k, i in enumerate(missing):
                    rows[i] = pulled[k]
                self.put_many(table, ids[missing],
                              np.asarray(pulled, np.float32))
                with self._lock:
                    self._pulled_rows += len(missing)
        if n < len(ids):
            # bucket padding repeats ids[0]: its row is already resolved
            rows[n:] = rows[0]
        return rows

    def _fallback_row(self, table: str,
                      dim: Optional[int]) -> Optional[np.ndarray]:
        if self.fallback == "mean":
            with self._lock:
                ent = self._mean.get(table)
                if ent is not None and ent[1] > 0:
                    return (ent[0] / ent[1]).astype(np.float32)
        if dim is None:
            with self._lock:
                for (t, _id), row in self._data.items():
                    if t == table:
                        dim = len(row)
                        break
        return np.zeros(dim, np.float32) if dim is not None else None

    # ------------------------------------------------------------------
    def get(self, table: str, idx: int) -> Optional[np.ndarray]:
        with self._lock:
            row = self._data.get((table, int(idx)))
            if row is not None:
                self._data.move_to_end((table, int(idx)))
                return row.copy()
            return None

    def put_many(self, table: str, ids, rows) -> None:
        """Insert rows (copies), evicting LRU past capacity."""
        ids = np.asarray(ids).reshape(-1)
        rows = np.asarray(rows, np.float32).reshape(len(ids), -1)
        with self._lock:
            data = self._data
            ent = self._mean.setdefault(
                table, [np.zeros(rows.shape[1], np.float64), 0])
            for k, idx in enumerate(ids):
                data[(table, int(idx))] = rows[k].copy()
                data.move_to_end((table, int(idx)))
                ent[0] += rows[k]
                ent[1] += 1
            while len(data) > self.capacity_rows:
                data.popitem(last=False)

    def invalidate_ids(self, table: str, ids) -> None:
        """Drop specific rows (a sparse-grad push changed them)."""
        with self._lock:
            for idx in np.asarray(ids).reshape(-1):
                self._data.pop((table, int(idx)), None)

    def invalidate(self, table: Optional[str] = None) -> None:
        """Drop a whole table's rows (or everything): the checkpoint-
        restore / table-assign path, where rows changed wholesale."""
        with self._lock:
            if table is None:
                self._data.clear()
                self._mean.clear()
            else:
                for key in [k for k in self._data if k[0] == table]:
                    del self._data[key]
                self._mean.pop(table, None)

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, object]:
        with self._lock:
            total = self._hits + self._misses
            return {
                "size_rows": len(self._data),
                "capacity_rows": self.capacity_rows,
                "hits": self._hits,
                "misses": self._misses,
                "fallback_rows": self._fallback_rows,
                "pulled_rows": self._pulled_rows,
                "hit_ratio": (round(self._hits / total, 6)
                              if total else None),
                "cache_only": self._cache_only,
            }

    def close(self) -> None:
        """Retire this cache's series from the exposition."""
        lbl = {"cache": self.name}
        for metric in (CACHE_HITS, CACHE_MISSES, CACHE_FALLBACK,
                       CACHE_HIT_RATIO):
            metric.remove_labels(**lbl)
        with self._lock:
            self._data.clear()
            self._mean.clear()

    # ------------------------------------------------------------------
    def bind(self, program) -> "EmbeddingRowCache":
        """Attach to a program (or an ``AnalysisPredictor``'s) so the
        executor's sparse prefetch reads through this cache."""
        target = getattr(program, "_program", program)
        target._embedding_cache = self
        return self
