"""Bucketed KV-cache slot pool for continuous-batching decode.

The serving batcher's zero-recompile story (``BucketPolicy``: pad every
batch onto a closed ladder of sizes, ``warmup()`` pre-compiles each
rung) extends here to AUTOREGRESSIVE state: a decode step's executable
is shaped by (slot count, cache length), so the pool quantizes both
onto ladders — ``slot_ladder`` rungs over batch slots x ``len_ladder``
rungs over sequence length — and AOT-compiles the two pure functions
the scheduler dispatches (``decoding.make_slot_decode_fns``: the
multi-step ``chunk`` and the seat-one-request ``admit``/``release``)
for every rung pair at :meth:`warmup`.  After warmup, a mixed
prompt/decode storm runs entirely on warmed executables — the pool's
:meth:`jit_cache_stats` is the recompile ground truth the serving
``/statusz`` reports, exactly like ``AnalysisPredictor`` on the
request-batching path.

The pool state is one dict pytree (slot axis 0 on every leaf; the KV
cache's T axis read by the step fn).  Buffer donation applies to the
state argument on every executable — the multi-MB KV cache updates in
place in device memory instead of being copied per tick — with the same
CPU carve-out as the executor (``executor._donate_kwargs``: donation +
the persistent compile cache corrupts fetches on the CPU backend).

Rung transitions (a storm outgrowing its slot rung, a long prompt
outgrowing the length rung) are CONTROL-PLANE operations: the state is
materialized host-side, zero-padded into the next rung's shapes with
plain numpy, and handed back to the (already warmed) larger
executables.  No XLA compile, no new shape — a transition costs one
d2h/h2d round trip, amortized over the thousands of decode steps that
follow.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.serving.bucketing import BucketPolicy

__all__ = ["KVSlotPool", "default_len_ladder"]


def default_len_ladder(max_seq_len: int, start: int = 8) -> List[int]:
    """Powers of two from ``start`` up to ``max_seq_len`` (appended when
    not itself a power of two) — the length-axis analog of the batch
    bucket ladder."""
    if max_seq_len < 1:
        raise ValueError("max_seq_len must be >= 1, got %r" % max_seq_len)
    ladder = []
    b = min(start, max_seq_len)
    while b < max_seq_len:
        ladder.append(b)
        b *= 2
    ladder.append(max_seq_len)
    return sorted(set(ladder))


class KVSlotPool:
    """Warmed executables + state plumbing for one decode endpoint.

    ``step_fn``/``make_cache``: the slot-pooled step builder's outputs
    (``decoding.make_transformer_lm_pooled_step_fn`` — per-row positions,
    cache T axis read from the cache itself, so ONE step fn serves every
    rung pair).  ``steps``: tokens advanced per ``chunk`` dispatch (the
    ``fori_loop`` multi-step amortization between scheduler
    interventions).

    ``on_recompile``: called (once per compile) when an executable is
    built AFTER :meth:`warmup` — the serving layer counts it as a
    recompile, the guarantee violation.
    """

    def __init__(self, step_fn: Callable, make_cache: Callable, *,
                 eos_id: int, max_slots: int, max_seq_len: int,
                 slot_ladder: Optional[Sequence[int]] = None,
                 len_ladder: Optional[Sequence[int]] = None,
                 steps: int = 4,
                 on_recompile: Optional[Callable[[], None]] = None):
        from paddle_tpu.decoding import make_slot_decode_fns

        self._make_cache = make_cache
        self.eos_id = int(eos_id)
        self.steps = max(1, int(steps))
        self.slot_policy = BucketPolicy(max_slots, slot_ladder)
        self.len_policy = BucketPolicy(
            max_seq_len, len_ladder or default_len_ladder(max_seq_len))
        self._fns = make_slot_decode_fns(step_fn, self.eos_id, self.steps)
        self._chunk_fn, self._admit_fn, self._release_fn = self._fns
        self._jitted = None  # built lazily (first compile / warmup)
        self._exe: Dict[Tuple[str, int, int], object] = {}
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        self.warmed = False
        self._on_recompile = on_recompile

    # ------------------------------------------------------------------
    @property
    def max_slots(self) -> int:
        return self.slot_policy.max_batch_size

    @property
    def max_seq_len(self) -> int:
        return self.len_policy.max_batch_size

    def rung_pairs(self) -> List[Tuple[int, int]]:
        return [(s, t) for s in self.slot_policy.ladder
                for t in self.len_policy.ladder]

    # ------------------------------------------------------------------
    def _jit(self):
        """The jitted (not yet shape-specialized) fns, built once.  The
        state argument is DONATED so the KV cache updates in place —
        except on CPU, where donation + the persistent compile cache is
        known-unsafe (executor._donate_kwargs pins the policy)."""
        if self._jitted is None:
            import jax

            from paddle_tpu.executor import _donate_kwargs

            kw = _donate_kwargs(jax.devices()[0])
            self._jitted = {
                "chunk": jax.jit(self._chunk_fn, **kw),
                "admit": jax.jit(self._admit_fn, **kw),
                "release": jax.jit(self._release_fn, **kw),
            }
        return self._jitted

    def _state_spec(self, s: int, t: int):
        """Abstract (ShapeDtypeStruct) pool state for rung pair
        ``(s, t)`` — shapes without allocating a byte (``jax.eval_shape``
        traces ``make_cache`` instead of running it)."""
        import jax

        cache = jax.eval_shape(lambda: self._make_cache(s, t))
        i32 = np.dtype(np.int32)
        return {
            "cache": cache,
            "tokens": jax.ShapeDtypeStruct((s, t), i32),
            "pos": jax.ShapeDtypeStruct((s,), i32),
            "prompt_len": jax.ShapeDtypeStruct((s,), i32),
            "total_len": jax.ShapeDtypeStruct((s,), i32),
            "active": jax.ShapeDtypeStruct((s,), np.dtype(bool)),
            "finished": jax.ShapeDtypeStruct((s,), np.dtype(bool)),
            "n_gen": jax.ShapeDtypeStruct((s,), i32),
        }

    def alloc(self, s: int, t: int) -> Dict[str, object]:
        """A fresh zeroed pool state for rung pair ``(s, t)``, HOST-side
        (plain numpy): device memory is first touched by the executable
        that consumes it, and an idle pool that dropped its state holds
        no HBM at all."""
        import jax

        return jax.tree.map(
            lambda sd: np.zeros(sd.shape, sd.dtype), self._state_spec(s, t))

    def resize(self, state, new_s: int, new_t: int) -> Dict[str, object]:
        """Re-shape ``state`` into rung pair ``(new_s, new_t)``
        host-side: every leaf is materialized (d2h), copied into a
        zero-padded (or sliced) buffer of the target rung's shape, and
        returned as numpy for the next executable call (h2d).  A pure
        control-plane move — no XLA compile is ever involved, so the
        zero-recompile guarantee survives rung transitions.  Shrinking
        assumes the caller vacated the dropped tail slots."""
        import jax

        spec = self._state_spec(new_s, new_t)

        def one(arr, sd):
            src = np.asarray(arr)
            if src.shape == sd.shape:
                return src
            out = np.zeros(sd.shape, sd.dtype)
            sl = tuple(slice(0, min(a, b))
                       for a, b in zip(src.shape, sd.shape))
            out[sl] = src[sl]
            return out

        return jax.tree.map(one, state, spec)

    @staticmethod
    def state_rungs(state) -> Tuple[int, int]:
        """The (slot, length) rung pair a state currently occupies."""
        s, t = state["tokens"].shape
        return int(s), int(t)

    # ------------------------------------------------------------------
    def _get_exe(self, kind: str, s: int, t: int):
        key = (kind, s, t)
        with self._lock:
            exe = self._exe.get(key)
            if exe is not None:
                self._hits += 1
                return exe
        exe = self._compile(kind, s, t)
        with self._lock:
            self._exe[key] = exe
            self._misses += 1
            if self.warmed and self._on_recompile is not None:
                self._on_recompile()
        return exe

    def _compile(self, kind: str, s: int, t: int):
        import jax

        spec = self._state_spec(s, t)
        jitted = self._jit()[kind]
        if kind == "chunk":
            return jitted.lower(spec).compile()
        i32 = np.dtype(np.int32)
        mask = jax.ShapeDtypeStruct((s,), np.dtype(bool))
        if kind == "release":
            return jitted.lower(spec, mask).compile()
        prompt = jax.ShapeDtypeStruct((t,), i32)
        scalar = jax.ShapeDtypeStruct((), i32)
        return jitted.lower(spec, mask, prompt, scalar, scalar).compile()

    # ------------------------------------------------------------------
    def warmup(self) -> int:
        """AOT-compile chunk + admit + release for EVERY rung pair;
        returns the number of compiles performed (0 on a re-warm).
        After this, a storm that stays inside the ladders never builds
        an executable again — :meth:`jit_cache_stats` ``misses`` is the
        proof the serving layer asserts on."""
        compiles = 0
        for s, t in self.rung_pairs():
            for kind in ("chunk", "admit", "release"):
                key = (kind, s, t)
                with self._lock:
                    have = key in self._exe
                if have:
                    continue
                exe = self._compile(kind, s, t)
                with self._lock:
                    self._exe[key] = exe
                compiles += 1
        self.warmed = True
        return compiles

    def jit_cache_stats(self) -> Dict[str, int]:
        """The recompile ground truth (same contract as
        ``AnalysisPredictor.jit_cache_stats``): ``misses`` counts built
        executables, ``hits`` runs served by an existing one."""
        with self._lock:
            return {"entries": len(self._exe), "hits": self._hits,
                    "misses": self._misses}

    # ------------------------------------------------------------------
    # dispatch (the scheduler's hot path: one dict lookup + one call)
    # ------------------------------------------------------------------
    def chunk(self, state) -> Dict[str, object]:
        """Advance every active slot by up to ``steps`` tokens in ONE
        device dispatch (prefill and decode interleaved inside)."""
        s, t = self.state_rungs(state)
        # hot-path: begin kv_chunk (executable lookup + async dispatch;
        # the scheduler materializes results OUTSIDE this region)
        exe = self._get_exe("chunk", s, t)
        out = exe(state)
        # hot-path: end kv_chunk
        return out

    def admit(self, state, slot: int, prompt: np.ndarray,
              prompt_len: int, total_len: int) -> Dict[str, object]:
        """Seat one request into free slot ``slot``: the prompt is
        padded host-side to the state's length rung and the slot's
        flags/cursors reset in ONE device dispatch (the cache passes
        through untouched — write-before-read makes zeroing a reused
        slot unnecessary)."""
        s, t = self.state_rungs(state)
        mask = np.zeros((s,), bool)
        mask[slot] = True
        buf = np.zeros((t,), np.int32)
        n = min(len(prompt), t)
        buf[:n] = np.asarray(prompt[:n], np.int32)
        # hot-path: begin kv_admit (executable lookup + async dispatch)
        exe = self._get_exe("admit", s, t)
        out = exe(state, mask, buf,
                  np.asarray(prompt_len, np.int32),  # hot-ok: host scalar
                  np.asarray(total_len, np.int32))  # hot-ok: host scalar
        # hot-path: end kv_admit
        return out

    def release(self, state, slots: Sequence[int]) -> Dict[str, object]:
        """Deactivate ``slots`` mid-flight (expired deadline, abort):
        their lanes stop advancing and become seatable again."""
        s, t = self.state_rungs(state)
        mask = np.zeros((s,), bool)
        for i in slots:
            mask[i] = True
        exe = self._get_exe("release", s, t)
        return exe(state, mask)
