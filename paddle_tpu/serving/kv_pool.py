"""Bucketed KV-cache slot pool for continuous-batching decode.

The serving batcher's zero-recompile story (``BucketPolicy``: pad every
batch onto a closed ladder of sizes, ``warmup()`` pre-compiles each
rung) extends here to AUTOREGRESSIVE state: a decode step's executable
is shaped by (slot count, cache length), so the pool quantizes both
onto ladders — ``slot_ladder`` rungs over batch slots x ``len_ladder``
rungs over sequence length — and AOT-compiles the two pure functions
the scheduler dispatches (``decoding.make_slot_decode_fns``: the
multi-step ``chunk`` and the seat-one-request ``admit``/``release``)
for every rung pair at :meth:`warmup`.  After warmup, a mixed
prompt/decode storm runs entirely on warmed executables — the pool's
:meth:`jit_cache_stats` is the recompile ground truth the serving
``/statusz`` reports, exactly like ``AnalysisPredictor`` on the
request-batching path.

The pool state is one dict pytree (slot axis 0 on every leaf; the KV
cache's T axis read by the step fn).  Buffer donation applies to the
state argument on every executable — the multi-MB KV cache updates in
place in device memory instead of being copied per tick — with the same
CPU carve-out as the executor (``executor._donate_kwargs``: donation +
the persistent compile cache corrupts fetches on the CPU backend).

Rung transitions (a storm outgrowing its slot rung, a long prompt
outgrowing the length rung) are CONTROL-PLANE operations: the state is
materialized host-side, zero-padded into the next rung's shapes with
plain numpy, and handed back to the (already warmed) larger
executables.  No XLA compile, no new shape — a transition costs one
d2h/h2d round trip, amortized over the thousands of decode steps that
follow.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.serving.bucketing import BucketPolicy

__all__ = ["KVSlotPool", "default_len_ladder"]


def default_len_ladder(max_seq_len: int, start: int = 8) -> List[int]:
    """Powers of two from ``start`` up to ``max_seq_len`` (appended when
    not itself a power of two) — the length-axis analog of the batch
    bucket ladder."""
    if max_seq_len < 1:
        raise ValueError("max_seq_len must be >= 1, got %r" % max_seq_len)
    ladder = []
    b = min(start, max_seq_len)
    while b < max_seq_len:
        ladder.append(b)
        b *= 2
    ladder.append(max_seq_len)
    return sorted(set(ladder))


class KVSlotPool:
    """Warmed executables + state plumbing for one decode endpoint.

    ``step_fn``/``make_cache``: the slot-pooled step builder's outputs
    (``decoding.make_transformer_lm_pooled_step_fn`` — per-row positions,
    cache T axis read from the cache itself, so ONE step fn serves every
    rung pair).  ``steps``: tokens advanced per ``chunk`` dispatch (the
    ``fori_loop`` multi-step amortization between scheduler
    interventions).

    ``on_recompile``: called (once per compile) when an executable is
    built AFTER :meth:`warmup` — the serving layer counts it as a
    recompile, the guarantee violation.
    """

    def __init__(self, step_fn: Callable, make_cache: Callable, *,
                 eos_id: int, max_slots: int, max_seq_len: int,
                 slot_ladder: Optional[Sequence[int]] = None,
                 len_ladder: Optional[Sequence[int]] = None,
                 steps: int = 4,
                 on_recompile: Optional[Callable[[], None]] = None,
                 prefix: bool = False,
                 speculative=None,
                 kv_dtype: str = "fp32",
                 len_multiple: int = 1):
        from paddle_tpu.decoding import (make_prefix_admit_fn,
                                         make_slot_decode_fns,
                                         normalize_kv_dtype)

        self._make_cache = make_cache
        # the cache storage dtype ``make_cache`` allocates (advertised
        # on /healthz; the pool itself is dtype-agnostic — shapes and
        # dtypes all flow from the state spec, so the int8 rung variant
        # with its sibling scale leaves rides resize/extract/admit
        # unchanged)
        self.kv_dtype = normalize_kv_dtype(kv_dtype)
        self.eos_id = int(eos_id)
        self.steps = max(1, int(steps))
        self.slot_policy = BucketPolicy(max_slots, slot_ladder)
        # ``len_multiple`` (sequence-parallel serving): every length
        # rung — and the cap itself — rounds UP to the next multiple,
        # so a pool feeding an sp-sharded model only ever compiles
        # sp-divisible sequence lengths (the ring layout's divisibility
        # rule holds on every rung, not just the top)
        self.len_multiple = max(1, int(len_multiple))
        ladder = list(len_ladder or default_len_ladder(max_seq_len))
        if self.len_multiple > 1:
            lm = self.len_multiple
            max_seq_len = -(-int(max_seq_len) // lm) * lm
            ladder = sorted({-(-int(t) // lm) * lm for t in ladder}
                            | {max_seq_len})
        self.len_policy = BucketPolicy(max_seq_len, ladder)
        # decode tier 2 (both default-off so the base pool's compiled
        # set — and its warmup count — are exactly the PR-9 three):
        # ``prefix`` adds the admit_prefix executable (shared-prefix KV
        # installation); ``speculative`` (a SpeculativeConfig) threads
        # the draft cache + spec flag through the state and adds the
        # fused draft+verify spec_chunk executable.
        self.prefix = bool(prefix)
        self.speculative = speculative
        self._fns = make_slot_decode_fns(
            step_fn, self.eos_id, self.steps,
            draft_step_fn=(speculative.draft_step_fn
                           if speculative is not None else None))
        self._chunk_fn, self._admit_fn, self._release_fn = self._fns
        self._admit_prefix_fn = (
            make_prefix_admit_fn(self._admit_fn) if self.prefix else None)
        if speculative is not None:
            from paddle_tpu.serving.speculative import make_spec_chunk_fn

            self._spec_chunk_fn = make_spec_chunk_fn(
                speculative.verify_fn, speculative.draft_step_fn,
                self.eos_id, speculative.k)
        else:
            self._spec_chunk_fn = None
        self._jitted = None  # built lazily (first compile / warmup)
        self._exe: Dict[Tuple[str, int, int], object] = {}
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        self.warmed = False
        self._on_recompile = on_recompile

    # ------------------------------------------------------------------
    @property
    def max_slots(self) -> int:
        return self.slot_policy.max_batch_size

    @property
    def max_seq_len(self) -> int:
        return self.len_policy.max_batch_size

    def rung_pairs(self) -> List[Tuple[int, int]]:
        return [(s, t) for s in self.slot_policy.ladder
                for t in self.len_policy.ladder]

    # ------------------------------------------------------------------
    def _jit(self):
        """The jitted (not yet shape-specialized) fns, built once.  The
        state argument is DONATED so the KV cache updates in place —
        except on CPU, where donation + the persistent compile cache is
        known-unsafe (executor._donate_kwargs pins the policy)."""
        if self._jitted is None:
            import jax

            from paddle_tpu.executor import _donate_kwargs

            kw = _donate_kwargs(jax.devices()[0])
            self._jitted = {
                "chunk": jax.jit(self._chunk_fn, **kw),
                "admit": jax.jit(self._admit_fn, **kw),
                "release": jax.jit(self._release_fn, **kw),
            }
            if self._admit_prefix_fn is not None:
                self._jitted["admit_prefix"] = jax.jit(
                    self._admit_prefix_fn, **kw)
            if self._spec_chunk_fn is not None:
                self._jitted["spec_chunk"] = jax.jit(
                    self._spec_chunk_fn, **kw)
        return self._jitted

    def _kinds(self) -> List[str]:
        """Every executable kind this pool compiles per rung pair."""
        kinds = ["chunk", "admit", "release"]
        if self.prefix:
            kinds.append("admit_prefix")
        if self.speculative is not None:
            kinds.append("spec_chunk")
        return kinds

    def _state_spec(self, s: int, t: int):
        """Abstract (ShapeDtypeStruct) pool state for rung pair
        ``(s, t)`` — shapes without allocating a byte (``jax.eval_shape``
        traces ``make_cache`` instead of running it)."""
        import jax

        cache = jax.eval_shape(lambda: self._make_cache(s, t))
        i32 = np.dtype(np.int32)
        spec = {
            "cache": cache,
            "tokens": jax.ShapeDtypeStruct((s, t), i32),
            "pos": jax.ShapeDtypeStruct((s,), i32),
            "prompt_len": jax.ShapeDtypeStruct((s,), i32),
            "total_len": jax.ShapeDtypeStruct((s,), i32),
            "active": jax.ShapeDtypeStruct((s,), np.dtype(bool)),
            "finished": jax.ShapeDtypeStruct((s,), np.dtype(bool)),
            "n_gen": jax.ShapeDtypeStruct((s,), i32),
        }
        if self.speculative is not None:
            spec["spec"] = jax.ShapeDtypeStruct((s,), np.dtype(bool))
            spec["draft_cache"] = jax.eval_shape(
                lambda: self.speculative.draft_make_cache(s, t))
        return spec

    def _kv_subtree_leaves(self, state_or_spec):
        """Flattened leaves of the state's KV subtrees (``cache`` plus
        ``draft_cache`` when speculative) — the fixed order the prefix
        cache stores and ``admit_prefix`` consumes."""
        import jax

        sub = {"cache": state_or_spec["cache"]}
        if "draft_cache" in state_or_spec:
            sub["draft_cache"] = state_or_spec["draft_cache"]
        leaves, _ = jax.tree_util.tree_flatten(sub)
        return leaves

    def alloc(self, s: int, t: int) -> Dict[str, object]:
        """A fresh zeroed pool state for rung pair ``(s, t)``, HOST-side
        (plain numpy): device memory is first touched by the executable
        that consumes it, and an idle pool that dropped its state holds
        no HBM at all."""
        import jax

        return jax.tree.map(
            lambda sd: np.zeros(sd.shape, sd.dtype), self._state_spec(s, t))

    def resize(self, state, new_s: int, new_t: int) -> Dict[str, object]:
        """Re-shape ``state`` into rung pair ``(new_s, new_t)``
        host-side: every leaf is materialized (d2h), copied into a
        zero-padded (or sliced) buffer of the target rung's shape, and
        returned as numpy for the next executable call (h2d).  A pure
        control-plane move — no XLA compile is ever involved, so the
        zero-recompile guarantee survives rung transitions.  Shrinking
        assumes the caller vacated the dropped tail slots."""
        import jax

        spec = self._state_spec(new_s, new_t)

        def one(arr, sd):
            src = np.asarray(arr)
            if src.shape == sd.shape:
                return src
            out = np.zeros(sd.shape, sd.dtype)
            sl = tuple(slice(0, min(a, b))
                       for a, b in zip(src.shape, sd.shape))
            out[sl] = src[sl]
            return out

        return jax.tree.map(one, state, spec)

    @staticmethod
    def state_rungs(state) -> Tuple[int, int]:
        """The (slot, length) rung pair a state currently occupies."""
        s, t = state["tokens"].shape
        return int(s), int(t)

    def kv_rung_bytes(self, s: int, t: int) -> int:
        """KV bytes one state of rung pair ``(s, t)`` holds (cache +
        sibling scale leaves + draft cache) — computed from the state
        SPEC's stored dtypes, no allocation.  This is the pool-
        accounting ground truth the ``serving_kv_cache_bytes`` gauge
        and the int8-KV capacity bench read: an int8 pool's rung holds
        ~4x less than fp32's, so a fixed HBM budget seats ~2x+ the
        concurrent sequences at the next slot rung up."""
        total = 0
        for leaf in self._kv_subtree_leaves(self._state_spec(s, t)):
            total += int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
        return int(total)

    def kv_state_bytes(self, state) -> int:
        """:meth:`kv_rung_bytes` for ``state``'s current rung pair."""
        s, t = self.state_rungs(state)
        return self.kv_rung_bytes(s, t)

    # ------------------------------------------------------------------
    def _get_exe(self, kind: str, s: int, t: int):
        key = (kind, s, t)
        with self._lock:
            exe = self._exe.get(key)
            if exe is not None:
                self._hits += 1
                return exe
        exe = self._compile(kind, s, t)
        with self._lock:
            self._exe[key] = exe
            self._misses += 1
            if self.warmed and self._on_recompile is not None:
                self._on_recompile()
        return exe

    def _compile(self, kind: str, s: int, t: int):
        import jax

        spec = self._state_spec(s, t)
        jitted = self._jit()[kind]
        if kind in ("chunk", "spec_chunk"):
            return jitted.lower(spec).compile()
        i32 = np.dtype(np.int32)
        mask = jax.ShapeDtypeStruct((s,), np.dtype(bool))
        if kind == "release":
            return jitted.lower(spec, mask).compile()
        prompt = jax.ShapeDtypeStruct((t,), i32)
        scalar = jax.ShapeDtypeStruct((), i32)
        args = [spec, mask, prompt, scalar, scalar]
        if kind == "admit_prefix":
            from paddle_tpu.decoding import kv_leaf_seq_axis

            kv = []
            for leaf in self._kv_subtree_leaves(spec):
                ax = kv_leaf_seq_axis(leaf.shape, s, t)
                kv.append(jax.ShapeDtypeStruct(
                    leaf.shape[1:] if ax is not None else (1,),
                    leaf.dtype if ax is not None
                    else np.dtype(np.float32)))
            args.append(kv)
            args.append(scalar)  # prefix_len
        if self.speculative is not None:
            args.append(jax.ShapeDtypeStruct((), np.dtype(bool)))
        return jitted.lower(*args).compile()

    # ------------------------------------------------------------------
    def warmup(self) -> int:
        """AOT-compile chunk + admit + release for EVERY rung pair;
        returns the number of compiles performed (0 on a re-warm).
        After this, a storm that stays inside the ladders never builds
        an executable again — :meth:`jit_cache_stats` ``misses`` is the
        proof the serving layer asserts on."""
        compiles = 0
        for s, t in self.rung_pairs():
            for kind in self._kinds():
                key = (kind, s, t)
                with self._lock:
                    have = key in self._exe
                if have:
                    continue
                exe = self._compile(kind, s, t)
                with self._lock:
                    self._exe[key] = exe
                compiles += 1
        self.warmed = True
        return compiles

    def jit_cache_stats(self) -> Dict[str, int]:
        """The recompile ground truth (same contract as
        ``AnalysisPredictor.jit_cache_stats``): ``misses`` counts built
        executables, ``hits`` runs served by an existing one."""
        with self._lock:
            return {"entries": len(self._exe), "hits": self._hits,
                    "misses": self._misses}

    # ------------------------------------------------------------------
    # dispatch (the scheduler's hot path: one dict lookup + one call)
    # ------------------------------------------------------------------
    def chunk(self, state) -> Dict[str, object]:
        """Advance every active slot by up to ``steps`` tokens in ONE
        device dispatch (prefill and decode interleaved inside)."""
        s, t = self.state_rungs(state)
        # hot-path: begin kv_chunk (executable lookup + async dispatch;
        # the scheduler materializes results OUTSIDE this region)
        exe = self._get_exe("chunk", s, t)
        out = exe(state)
        # hot-path: end kv_chunk
        return out

    def admit(self, state, slot: int, prompt: np.ndarray,
              prompt_len: int, total_len: int,
              spec: bool = False) -> Dict[str, object]:
        """Seat one request into free slot ``slot``: the prompt is
        padded host-side to the state's length rung and the slot's
        flags/cursors reset in ONE device dispatch (the cache passes
        through untouched — write-before-read makes zeroing a reused
        slot unnecessary).  ``spec`` marks the slot for speculative
        rounds (ignored unless the pool was built with a
        SpeculativeConfig)."""
        s, t = self.state_rungs(state)
        mask, buf = self._admit_host_args(s, t, slot, prompt)
        # hot-path: begin kv_admit (executable lookup + async dispatch)
        exe = self._get_exe("admit", s, t)
        args = [state, mask, buf,
                np.asarray(prompt_len, np.int32),  # hot-ok: host scalar
                np.asarray(total_len, np.int32)]  # hot-ok: host scalar
        if self.speculative is not None:
            args.append(np.asarray(bool(spec)))  # hot-ok: host scalar
        out = exe(*args)
        # hot-path: end kv_admit
        return out

    def _admit_host_args(self, s: int, t: int, slot: int, prompt):
        mask = np.zeros((s,), bool)
        mask[slot] = True
        buf = np.zeros((t,), np.int32)
        n = min(len(prompt), t)
        buf[:n] = np.asarray(prompt[:n], np.int32)
        return mask, buf

    def admit_prefix(self, state, slot: int, prompt: np.ndarray,
                     prompt_len: int, total_len: int,
                     kv_leaves, prefix_len: int,
                     spec: bool = False) -> Dict[str, object]:
        """Seat a request whose first ``prefix_len`` positions are
        served from retained KV blocks (``kv_leaves``: the prefix
        cache's stored leaf list, per :meth:`extract_kv` order): the
        leaves are host-padded to the current length rung and installed
        by the warmed ``admit_prefix`` executable, and the slot starts
        at ``pos = prefix_len`` — prefill resumes at the unmatched
        suffix.  Requires ``prefix=True`` at construction."""
        from paddle_tpu.decoding import kv_leaf_seq_axis

        if self._admit_prefix_fn is None:
            raise RuntimeError(
                "pool was built without prefix=True — admit_prefix has "
                "no warmed executable")
        s, t = self.state_rungs(state)
        mask, buf = self._admit_host_args(s, t, slot, prompt)
        spec_leaves = self._kv_subtree_leaves(self._state_spec(s, t))
        kv = []
        for sd, ent in zip(spec_leaves, kv_leaves):
            ax = kv_leaf_seq_axis(sd.shape, s, t)
            if ax is None or ent is None:
                kv.append(np.zeros((1,), np.float32))
                continue
            tgt = np.zeros(sd.shape[1:], sd.dtype)
            sl = [slice(0, min(a, b))
                  for a, b in zip(ent.shape, tgt.shape)]
            tgt[tuple(sl)] = ent[tuple(sl)]
            kv.append(tgt)
        # hot-path: begin kv_admit_prefix (executable lookup + async
        # dispatch; the leaf re-pad above is host numpy on stored
        # host arrays — no device sync)
        exe = self._get_exe("admit_prefix", s, t)
        args = [state, mask, buf,
                np.asarray(prompt_len, np.int32),  # hot-ok: host scalar
                np.asarray(total_len, np.int32),  # hot-ok: host scalar
                kv,
                np.asarray(prefix_len, np.int32)]  # hot-ok: host scalar
        if self.speculative is not None:
            args.append(np.asarray(bool(spec)))  # hot-ok: host scalar
        out = exe(*args)
        # hot-path: end kv_admit_prefix
        return out

    def extract_kv(self, state, slot: int, m: int):
        """Materialize slot ``slot``'s first ``m`` KV positions as host
        arrays (the prefix cache's retained-entry payload): one list
        entry per KV subtree leaf (:func:`decoding.kv_leaf_seq_axis`
        order), ``None`` for leaves carrying no per-slot sequence
        state.  A control-plane d2h — called when a slot is FREED, off
        the tick's dispatch path."""
        from paddle_tpu.decoding import kv_leaf_seq_axis

        s, t = self.state_rungs(state)
        out = []
        for leaf in self._kv_subtree_leaves(state):
            ax = kv_leaf_seq_axis(tuple(leaf.shape), s, t)
            if ax is None:
                out.append(None)
                continue
            sl = [slice(None)] * (leaf.ndim - 1)
            sl[ax - 1] = slice(0, int(m))
            out.append(np.asarray(leaf[slot][tuple(sl)]))
        return out

    def release(self, state, slots: Sequence[int]) -> Dict[str, object]:
        """Deactivate ``slots`` mid-flight (expired deadline, abort):
        their lanes stop advancing and become seatable again."""
        s, t = self.state_rungs(state)
        mask = np.zeros((s,), bool)
        for i in slots:
            mask[i] = True
        exe = self._get_exe("release", s, t)
        return exe(state, mask)
