"""Overload control: deadline-aware admission, priority shedding, AIMD.

PR 1's admission control was a fixed-capacity FIFO: a full queue shed
the newest arrival, whatever its deadline or importance, and the
capacity number was a static guess at what the chip could absorb.  This
module replaces the queue's POLICY while the coalescing mechanics stay
in ``batching.DynamicBatcher``:

* **Earliest-deadline-first ordering** — the queue is a heap keyed by
  deadline (no-deadline requests sort last, FIFO among themselves), so
  the next batch always starts from the request closest to giving up,
  and *expired* entries surface at the top where the sweep drops them
  with a typed ``DeadlineExceeded`` instead of burning a batch slot.
* **Priority classes** — every request carries a small-int priority
  (``PRIORITY_HIGH=0`` < ``PRIORITY_NORMAL=1`` < ``PRIORITY_LOW=2``;
  any int works, lower = more important).  A full queue sheds *the
  lowest-priority, least-urgent queued entry* to admit a more important
  arrival — under pressure low priority is shed first, never silently
  reordered.
* **An adaptive admit limit (AIMD)** — the effective queue bound floats
  between ``min_limit`` and the configured capacity, multiplicatively
  halved when the observed queue wait overshoots ``target_wait_ms`` and
  additively grown (+1) while it stays under — so the backlog tracks
  what the chip actually absorbs instead of a config constant.  Exposed
  as the ``serving_admit_limit`` gauge.
* **A computed retry hint** — every shed carries ``retry_after_ms``
  (EWMA queue wait scaled by the overload ratio) on the
  ``ServerOverloaded`` it raises; the wire layer forwards it as
  response meta + an HTTP ``Retry-After`` header and the fleet
  balancer's retry pacing honors it.
* **Weighted fair sharing across classes** — under STEADY saturation,
  pure priority ordering starves LOW entirely (every pop goes to a
  more important class that never drains).  The store is therefore one
  EDF heap PER CLASS, and pops are stride-scheduled across the
  non-empty classes by ``class_weights`` (default HIGH 4 : NORMAL 2 :
  LOW 1): each class owns a virtual-time pass advanced by
  ``1/weight`` per pop, and the smallest pass is served next — so LOW
  gets a deterministic trickle (1 pop in 7 under three-way
  saturation) instead of zero, while EDF order is preserved WITHIN
  each class.  ``class_weights=None`` disables sharing and restores
  the pure cross-class EDF pop order.

``BrownoutController`` is the deterministic degradation ladder the
server climbs under *sustained* saturation (ratio thresholds held for
``hold_s``): L1 drops flight-recorder capture, L2 forces eager batching
(batch window 0), L3 sheds the lowest priority class at admission.
Descent is slower than ascent (hysteresis) so the ladder doesn't
flap.  Exposed as the ``serving_brownout_level`` gauge.
"""
from __future__ import annotations

import heapq
import threading
import time
from typing import Dict, List, Optional, Tuple

from paddle_tpu import monitor
from paddle_tpu.monitor import events as _events

__all__ = [
    "PRIORITY_HIGH", "PRIORITY_NORMAL", "PRIORITY_LOW",
    "DEFAULT_CLASS_WEIGHTS", "AdmissionQueue", "BrownoutController",
]

PRIORITY_HIGH = 0
PRIORITY_NORMAL = 1
PRIORITY_LOW = 2

#: default stride-scheduling shares (lower class int = more important):
#: under three-way saturation HIGH gets 4 of every 7 pops, NORMAL 2,
#: LOW 1 — a deterministic trickle instead of starvation.  A class not
#: in the map weighs 1.
DEFAULT_CLASS_WEIGHTS = {
    PRIORITY_HIGH: 4.0, PRIORITY_NORMAL: 2.0, PRIORITY_LOW: 1.0,
}

_NO_DEADLINE = float("inf")

ADMIT_LIMIT = monitor.gauge(
    "serving_admit_limit",
    "current adaptive admission limit (AIMD on observed queue wait vs "
    "the latency target)", ("server",))
BROWNOUT_LEVEL = monitor.gauge(
    "serving_brownout_level",
    "degradation-ladder level under sustained saturation (0=normal, "
    "1=no flight capture, 2=eager batching, 3=shed lowest priority, "
    "4=cache-only embedding lookups on endpoints with a bound cache)",
    ("server",))
ADMISSION_EXPIRED = monitor.counter(
    "admission_expired_total",
    "requests shed at admission because their deadline had already "
    "passed (wire deadline propagation fail-fast)", ("server",))


class _Entry:
    """One queued request: EDF heap key, the admission priority, and a
    tombstone flag (priority shedding removes entries lazily — the heap
    is never re-built).  Request attributes are read ONCE at admission
    (duck-typed stubs without priority/deadline still work)."""

    __slots__ = ("key", "seq", "req", "priority", "alive")

    def __init__(self, key: float, seq: int, req, priority: int):
        self.key = key
        self.seq = seq
        self.req = req
        self.priority = priority
        self.alive = True

    def __lt__(self, other: "_Entry") -> bool:
        return (self.key, self.seq) < (other.key, other.seq)


class AdmissionQueue:
    """The bounded, deadline-ordered, priority-shedding request store
    behind ``DynamicBatcher``.

    Locking: ``cv`` is the queue's condition variable (the batcher's
    wakeup channel — submitters notify, the single consumer waits).
    ``*_locked`` methods require it held; ``offer`` takes it itself and
    returns the requests it dropped so the CALLER fails them outside
    the lock (no user callbacks run under ``cv``).
    """

    # AIMD cadence: adjust after this many pops or this much time,
    # whichever comes first (per-pop adjustment would thrash the limit)
    _ADJUST_EVERY = 16
    _ADJUST_INTERVAL_S = 0.25
    # EWMA smoothing for the observed queue wait
    _EWMA_ALPHA = 0.2

    def __init__(self, capacity: int, target_wait_ms: float = 50.0,
                 min_limit: int = 4, name: str = "server",
                 adaptive: bool = True,
                 class_weights: Optional[Dict[int, float]] = "default"):
        # queue.Queue convention kept from the FIFO version: <= 0 means
        # unbounded (no shedding, no adaptive limit)
        self.capacity = int(capacity) if int(capacity) > 0 else None
        self.target_wait_s = float(target_wait_ms) / 1e3
        # the AIMD floor can never exceed the configured capacity (a
        # decrease must not GROW the limit past the hard bound)
        self.min_limit = max(1, int(min_limit))
        if self.capacity is not None:
            self.min_limit = min(self.min_limit, self.capacity)
        self.adaptive = bool(adaptive) and self.capacity is not None
        self.name = name
        self.cv = threading.Condition()
        # the store: one EDF heap PER PRIORITY CLASS, so weighted fair
        # sharing can stride-schedule pops across classes while EDF
        # order is preserved within each
        if class_weights == "default":
            class_weights = DEFAULT_CLASS_WEIGHTS
        self.class_weights = (
            {int(k): float(v) for k, v in class_weights.items()}
            if class_weights is not None else None)
        if self.class_weights is not None and any(
                w <= 0 for w in self.class_weights.values()):
            raise ValueError(
                "class weights must be positive, got %r" % class_weights)
        self._heaps: Dict[int, List[_Entry]] = {}
        self._class_live: Dict[int, int] = {}
        # stride scheduling state: each class owns a virtual-time pass
        # advanced by 1/weight per pop; the smallest pass serves next.
        # _global_pass anchors a class waking from empty so an idle
        # class can never bank credit and then monopolize the queue.
        self._pass: Dict[int, float] = {}
        self._global_pass = 0.0
        self._live = 0
        self._seq = 0
        self._limit = self.capacity if self.capacity is not None else 0
        self._wait_ewma = 0.0   # seconds, EWMA of observed queue wait
        self._pops_since_adjust = 0
        self._last_adjust = time.monotonic()
        self._gauge = ADMIT_LIMIT.labels(server=name)
        if self.capacity is not None:
            self._gauge.set(self._limit)

    # ------------------------------------------------------------------
    @property
    def limit(self) -> int:
        """Current effective admit limit (the AIMD output)."""
        return self._limit if self.capacity is not None else 0

    def qsize(self) -> int:
        with self.cv:
            return self._live

    def depth_ratio(self) -> float:
        """Queue pressure in [0, ~1]: live entries / admit limit (0 for
        an unbounded queue — brownout needs a bound to define 'full')."""
        with self.cv:
            if self.capacity is None or self._limit <= 0:
                return 0.0
            return self._live / float(self._limit)

    @property
    def wait_ewma_ms(self) -> float:
        """The observed queue-wait EWMA in ms (the signal the AIMD
        limit and the ladder autotuner's batch-window proposal read)."""
        with self.cv:
            return self._wait_ewma * 1e3

    def retry_after_ms(self) -> float:
        """The shed hint: how long a rejected caller should back off —
        the EWMA queue wait scaled by the current overload ratio, never
        under 1ms (a 0 hint would invite an immediate re-storm)."""
        with self.cv:
            return self._retry_after_locked()

    def _retry_after_locked(self) -> float:
        ratio = 1.0
        if self.capacity is not None and self._limit > 0:
            ratio = max(1.0, self._live / float(self._limit))
        return max(1.0, self._wait_ewma * 1e3 * ratio)

    # ------------------------------------------------------------------
    @staticmethod
    def _key(req) -> float:
        deadline = getattr(req, "deadline", None)
        return deadline if deadline is not None else _NO_DEADLINE

    def offer(self, req) -> Tuple[bool, List, List, float]:
        """Try to admit ``req``.  Returns ``(admitted, expired, shed,
        retry_after_ms)``: ``expired`` are entries the sweep dropped
        (deadline passed while queued), ``shed`` are lower-priority
        entries evicted to make room.  The caller fails both lists
        typed, outside the lock, and raises ``ServerOverloaded``
        carrying ``retry_after_ms`` when not admitted."""
        expired: List = []
        shed: List = []
        # hot-path: begin admission_offer (heap push + bounded sweep
        # under the queue CV; no sleeps, no device syncs)
        priority = int(getattr(req, "priority", PRIORITY_NORMAL))
        with self.cv:
            now = time.monotonic()
            self._sweep_locked(now, expired)
            admitted = True
            if self.capacity is not None and self._live >= self._limit:
                victim = self._pick_victim_locked(priority)
                if victim is None:
                    admitted = False
                else:
                    victim.alive = False
                    self._live -= 1
                    self._class_live[victim.priority] -= 1
                    shed.append(victim.req)
            retry_ms = self._retry_after_locked()
            if admitted:
                self._seq += 1
                live = self._class_live.get(priority, 0)
                if live == 0 and self.class_weights is not None:
                    # a class waking from empty joins at the CURRENT
                    # virtual time: idle never banks credit
                    self._pass[priority] = max(
                        self._pass.get(priority, 0.0), self._global_pass)
                heapq.heappush(
                    self._heaps.setdefault(priority, []),
                    _Entry(self._key(req), self._seq, req, priority))
                self._class_live[priority] = live + 1
                self._live += 1
                self.cv.notify()
        # hot-path: end admission_offer
        return admitted, expired, shed, retry_ms

    def _sweep_locked(self, now: float, expired: List) -> None:
        """Drop dead/expired entries off every class heap's top.  EDF
        makes this complete per heap: every expired entry keys earlier
        than every live one (no-deadline entries key at +inf), so
        expired work can only sit at a top — the sweep never has to
        scan a heap's middle."""
        for cls, heap in self._heaps.items():
            while heap:
                top = heap[0]
                if not top.alive:
                    heapq.heappop(heap)
                    continue
                if top.key is not _NO_DEADLINE and top.key <= now:
                    heapq.heappop(heap)
                    top.alive = False
                    self._live -= 1
                    self._class_live[cls] -= 1
                    expired.append(top.req)
                    continue
                break

    def _pick_victim_locked(self, priority: int) -> Optional[_Entry]:
        """The entry priority shedding evicts for an arrival at
        ``priority``: the strictly-lower-priority entry with the latest
        deadline (least urgent loses).  None when every queued entry is
        at least as important as the arrival — then the ARRIVAL sheds.
        O(n) scan, but only ever on the shed path of a full queue."""
        victim = None
        for cls, heap in self._heaps.items():
            if cls <= priority:
                continue
            for ent in heap:
                if not ent.alive:
                    continue
                if victim is None or (
                        (ent.priority, ent.key, ent.seq)
                        > (victim.priority, victim.key, victim.seq)):
                    victim = ent
        return victim

    def _next_class_locked(self) -> Optional[int]:
        """The class the next pop serves.  ``class_weights=None``: pure
        cross-class EDF (the globally earliest deadline wins, FIFO on
        ties).  With weights: stride scheduling — the non-empty class
        with the smallest virtual-time pass wins, so every class drains
        in proportion to its weight and none starves."""
        best = None
        best_rank = None
        for cls, heap in self._heaps.items():
            if not self._class_live.get(cls) or not heap:
                continue
            top = heap[0]
            if self.class_weights is None:
                rank = (top.key, top.seq)
            else:
                rank = (self._pass.get(cls, 0.0), cls)
            if best_rank is None or rank < best_rank:
                best, best_rank = cls, rank
        return best

    # ------------------------------------------------------------------
    def pop_locked(self, now: Optional[float] = None
                   ) -> Tuple[Optional[object], List]:
        """Pop the next live request (None when empty) and the expired
        entries swept on the way: earliest deadline within the class the
        fair-share scheduler picked (see ``_next_class_locked``).
        Records the popped request's queue wait into the AIMD
        controller.  Caller holds ``cv`` and fails the expired list
        outside the lock."""
        expired: List = []
        now = time.monotonic() if now is None else now
        # hot-path: begin admission_pop (heap pop + AIMD arithmetic
        # under the queue CV; no sleeps, no device syncs)
        self._sweep_locked(now, expired)
        cls = self._next_class_locked()
        if cls is None:
            return None, expired
        ent = heapq.heappop(self._heaps[cls])
        ent.alive = False
        self._live -= 1
        self._class_live[cls] -= 1
        if self.class_weights is not None:
            cur = self._pass.get(cls, 0.0)
            self._global_pass = cur
            self._pass[cls] = cur + 1.0 / self.class_weights.get(cls, 1.0)
        submit_t = getattr(ent.req, "submit_t", None)
        if submit_t is not None:
            self._observe_locked(
                max(0.0, time.perf_counter() - submit_t), now)
        # hot-path: end admission_pop
        return ent.req, expired

    def _observe_locked(self, wait_s: float, now: float) -> None:
        """One observed queue wait -> the AIMD controller.  Overshoot of
        the target halves the admit limit (multiplicative decrease);
        staying under grows it by 1 (additive increase)."""
        self._wait_ewma += self._EWMA_ALPHA * (wait_s - self._wait_ewma)
        if not self.adaptive:
            return
        self._pops_since_adjust += 1
        if (self._pops_since_adjust < self._ADJUST_EVERY
                and now - self._last_adjust < self._ADJUST_INTERVAL_S):
            return
        self._pops_since_adjust = 0
        self._last_adjust = now
        if self._wait_ewma > self.target_wait_s:
            self._limit = max(self.min_limit, self._limit // 2)
        elif self._limit < self.capacity:
            self._limit += 1
        self._gauge.set(self._limit)

    # ------------------------------------------------------------------
    def drain_locked(self) -> List:
        """Pop and return every live queued request (shutdown).  Caller
        holds ``cv``.  Drained in strict priority order (HIGH first,
        EDF/FIFO within each class) — NOT the weighted stride order
        dispatch follows; shutdown fails everything anyway, so only a
        stable, explainable order matters here."""
        out = []
        for heap in self._heaps.values():
            out.extend(e for e in heap if e.alive)
        out.sort(key=lambda e: (e.priority, e.key, e.seq))
        self._heaps = {}
        self._class_live = {}
        self._live = 0
        return [e.req for e in out]

    def close(self) -> None:
        """Retire this queue's gauge series from the exposition."""
        ADMIT_LIMIT.remove_labels(server=self.name)


class BrownoutController:
    """The deterministic degradation ladder.

    ``update(ratio)`` is called by the server's dispatcher with the
    current queue pressure (``AdmissionQueue.depth_ratio``); the level
    climbs one rung at a time when the pressure has stayed at or above
    that rung's threshold for ``hold_s`` (sustained saturation, not a
    blip) and descends — one rung, slower (``4 * hold_s``) — when it
    has stayed below.  Levels:

      0  normal
      1  drop flight-recorder capture (tracing rent off the hot path)
      2  force the batch window to 0 (eager batching: ship what's here)
      3  shed the lowest priority class at admission
      4  (embedding-cache endpoints only) serve lookups CACHE-ONLY —
         misses get the fallback row instead of queuing on PS pulls

    The rung count is the threshold tuple's length: the default ladder
    stops at 3; an ``InferenceServer`` with a bound
    ``EmbeddingRowCache`` passes a 4-threshold ladder so the cache-only
    rung exists exactly where it has a cache to serve from.  The same
    hold/4x-hysteresis machinery governs every rung, so the cache-only
    mode enters late and exits slowly (no flapping between stale-tier
    and PS-tier serving).

    Deterministic by construction: level changes are a pure function of
    the (ratio, clock) series — chaos tests drive it with an injected
    clock and assert exact transitions.
    """

    #: pressure at or above which each level (1, 2, 3) wants to engage
    THRESHOLDS = (0.5, 0.75, 0.9)
    #: the cache-only rung's threshold when a 4-rung ladder is built
    CACHE_ONLY_THRESHOLD = 0.97
    MAX_LEVEL = 3

    def __init__(self, name: str = "server", hold_s: float = 0.25,
                 clock=time.monotonic, thresholds=None):
        self.name = name
        self.hold_s = float(hold_s)
        self.thresholds = (tuple(float(t) for t in thresholds)
                           if thresholds is not None else self.THRESHOLDS)
        if list(self.thresholds) != sorted(self.thresholds):
            raise ValueError(
                "brownout thresholds must ascend, got %r"
                % (self.thresholds,))
        self.max_level = len(self.thresholds)
        self._clock = clock
        self.level = 0
        self._pending: Optional[Tuple[int, float]] = None  # (direction, since)
        # update() is called from the dispatcher loop AND the submit
        # path (an L3 door-shed must still be able to descend when only
        # low-priority traffic arrives — with nothing enqueued the
        # dispatcher stays parked and would never sample again)
        self._lock = threading.Lock()
        self._gauge = BROWNOUT_LEVEL.labels(server=name)
        self._gauge.set(0)

    def _target(self, ratio: float) -> int:
        lvl = 0
        for i, thr in enumerate(self.thresholds):
            if ratio >= thr:
                lvl = i + 1
        return lvl

    def update(self, ratio: float, now: Optional[float] = None) -> int:
        """Fold one pressure sample; returns the (possibly new) level.
        Thread-safe: sampled by the dispatcher each turn and by the
        submit path at the L3 door."""
        now = self._clock() if now is None else now
        with self._lock:
            target = self._target(ratio)
            if target == self.level:
                self._pending = None
                return self.level
            direction = 1 if target > self.level else -1
            if self._pending is None or self._pending[0] != direction:
                self._pending = (direction, now)
                return self.level
            hold = self.hold_s if direction > 0 else 4.0 * self.hold_s
            if now - self._pending[1] >= hold:
                self.level += direction
                self._pending = None
                self._gauge.set(self.level)
                # event ring + span-stream instant in one call; a level
                # RISE is degradation (warning), easing back is info
                _events.emit(
                    "serving/brownout",
                    severity="warning" if direction > 0 else "info",
                    cat="serving", server=self.name, level=self.level)
            return self.level

    def close(self) -> None:
        BROWNOUT_LEVEL.remove_labels(server=self.name)
