"""Serving metrics registry.

Thread-safe counters + a bounded latency reservoir + a per-bucket
occupancy histogram, exposed two ways:

* ``snapshot()`` — a plain dict (QPS, p50/p99 latency, mean batch
  occupancy, shed/expired counts, recompile counter) for tests, bench
  drivers, and admin endpoints;
* per-batch events routed through ``paddle_tpu.profiler`` — each
  executed batch is timed under a ``RecordEvent`` (so it shows in the
  stop_profiler() host table) and emitted to the active JSONL trace
  sink via ``profiler.emit_trace_event`` for offline tail analysis.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict

import numpy as np

from paddle_tpu import profiler

__all__ = ["ServingMetrics"]

_RESERVOIR = 8192  # latencies kept for the percentile estimate


class ServingMetrics:
    def __init__(self, name: str = "server"):
        self.name = name
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._counters = {
            "requests": 0,       # admitted into the queue
            "completed": 0,      # results delivered
            "failed": 0,         # completed with a non-deadline error
            "shed": 0,           # rejected at admission (queue full)
            "expired": 0,        # deadline passed before a result
            "batches": 0,        # predictor executions
            "warmup_compiles": 0,
            "recompiles": 0,     # jit-cache misses AFTER warmup
        }
        self._latencies: deque = deque(maxlen=_RESERVOIR)  # seconds, per request
        # bucket -> [n_batches, total_valid_rows]
        self._occupancy: Dict[int, list] = {}

    # ------------------------------------------------------------------
    def count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counters[key] += n

    def observe_request(self, latency_s: float) -> None:
        with self._lock:
            self._counters["completed"] += 1
            self._latencies.append(latency_s)

    def observe_batch(self, valid: int, bucket: int, run_s: float,
                      recompiled: bool = False) -> None:
        """Record one executed batch and emit its trace event."""
        with self._lock:
            self._counters["batches"] += 1
            if recompiled:
                self._counters["recompiles"] += 1
            ent = self._occupancy.setdefault(bucket, [0, 0])
            ent[0] += 1
            ent[1] += valid
        profiler.emit_trace_event({
            "event": "serving.batch",
            "server": self.name,
            "valid": int(valid),
            "bucket": int(bucket),
            "run_ms": round(run_s * 1e3, 3),
            "recompiled": bool(recompiled),
        })

    # ------------------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        """Point-in-time metrics dict (the admin/bench surface)."""
        with self._lock:
            counters = dict(self._counters)
            lats = np.asarray(self._latencies, dtype=np.float64)
            occupancy = {b: tuple(v) for b, v in self._occupancy.items()}
            elapsed = time.perf_counter() - self._t0
        snap: Dict[str, object] = dict(counters)
        snap["elapsed_s"] = round(elapsed, 3)
        snap["qps"] = round(counters["completed"] / elapsed, 2) if elapsed > 0 else 0.0
        if lats.size:
            snap["latency_p50_ms"] = round(float(np.percentile(lats, 50)) * 1e3, 3)
            snap["latency_p99_ms"] = round(float(np.percentile(lats, 99)) * 1e3, 3)
        else:
            snap["latency_p50_ms"] = snap["latency_p99_ms"] = None
        total_rows = sum(b * n for b, (n, _) in occupancy.items())
        total_valid = sum(v for _, v in occupancy.values())
        snap["mean_batch_occupancy"] = (
            round(total_valid / total_rows, 4) if total_rows else None)
        snap["batch_histogram"] = {
            str(b): {"batches": n, "valid_rows": v}
            for b, (n, v) in sorted(occupancy.items())
        }
        return snap
