"""Serving metrics — a view over the framework-wide registry.

Since the monitor refactor, counters and the latency histogram live in
``paddle_tpu.monitor.REGISTRY`` (labeled ``server=<name>,
instance=<k>``), so serving shows up in the same ``/metrics`` text
exposition and ``monitor.snapshot()`` as the executor and reader
metrics.  This class keeps the per-SERVER-INSTANCE bookkeeping exact:

* ``snapshot()`` — a plain dict (QPS, p50/p99 latency, mean batch
  occupancy, shed/expired counts, recompile counter) for tests, bench
  drivers, and the ``/statusz`` endpoint, reading THIS instance's
  registry children (two servers with the same name get distinct
  ``instance`` labels, so counts never bleed across constructions);
* a bounded latency reservoir for exact p50/p99 (the registry histogram
  carries the bucketed exposition view of the same observations);
* per-batch events routed through ``paddle_tpu.profiler`` — each
  executed batch is timed under a ``RecordEvent`` (visible in the
  stop_profiler() table and any active monitor trace session) and
  emitted to the active JSONL trace sink for offline tail analysis.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Dict, Optional

import numpy as np

from paddle_tpu import monitor, profiler

__all__ = ["ServingMetrics"]

_RESERVOIR = 8192  # latencies kept for the percentile estimate

_COUNTER_HELP = {
    "requests": "admitted into the queue",
    "completed": "results delivered",
    "failed": "completed with a non-deadline error",
    "shed": "rejected at admission (queue full)",
    "expired": "deadline passed before a result",
    "batches": "predictor executions",
    "warmup_compiles": "XLA compiles performed by warmup()",
    "recompiles": "jit-cache misses AFTER warmup",
    "requeued": "batches re-routed off a failed/removed replica",
    # decode tier 2 (zero on non-decode servers)
    "prefix_fallback": "shared-prefix admissions that fell back to a "
                       "full prefill (corrupted/evicted-mid-admit "
                       "entry — degraded, never wrong tokens)",
    "prefix_store_failed": "freed-slot prefix KV offers that failed to "
                           "extract or store (the entry is simply not "
                           "retained)",
}
_LABELS = ("server", "instance")
_COUNTERS = {
    key: monitor.counter("serving_%s_total" % key, help, _LABELS)
    for key, help in _COUNTER_HELP.items()
}
_LATENCY = monitor.histogram(
    "serving_request_latency_seconds",
    "submit-to-complete request latency", _LABELS)
_BATCH_ROWS = monitor.counter(
    "serving_batch_rows_total",
    "rows in executed padded batches (bucket size x batches)", _LABELS)
_BATCH_VALID_ROWS = monitor.counter(
    "serving_batch_valid_rows_total",
    "valid (non-padding) rows in executed batches", _LABELS)
_PRECISION_REQS = monitor.counter(
    "serving_precision_requests_total",
    "requests served per compiled precision variant",
    _LABELS + ("dtype",))
_LADDER_REPLANS = monitor.counter(
    "serving_ladder_replans_total",
    "bucket-ladder re-plans applied behind the warmup barrier",
    _LABELS)
_PADDING_WASTE = monitor.gauge(
    "serving_padding_waste_ratio",
    "cumulative padding rows / padded rows for this endpoint (the "
    "bucket ladder's rent; the autotuner's objective)", _LABELS)
_PIPELINE_BUBBLE = monitor.gauge(
    "serving_pipeline_bubble_ratio",
    "structural GPipe bubble of a pipelined replica's last executed "
    "schedule, (K-1)/(M+K-1) — the idle fraction the micro-batch count "
    "amortizes", _LABELS)
_PIPELINE_OCCUPANCY = monitor.gauge(
    "serving_pipeline_stage_occupancy",
    "fraction of schedule slots each pipeline stage spends computing "
    "(M/(M+K-1)); one series per stage coordinate", _LABELS + ("stage",))

# distinguishes same-named servers constructed in one process
_instance_seq = itertools.count()


class ServingMetrics:
    def __init__(self, name: str = "server"):
        self.name = name
        self.instance = str(next(_instance_seq))
        lbl = {"server": name, "instance": self.instance}
        self._c = {key: m.labels(**lbl) for key, m in _COUNTERS.items()}
        self._latency = _LATENCY.labels(**lbl)
        self._batch_rows = _BATCH_ROWS.labels(**lbl)
        self._batch_valid = _BATCH_VALID_ROWS.labels(**lbl)
        self._replans = _LADDER_REPLANS.labels(**lbl)
        self._waste_gauge = _PADDING_WASTE.labels(**lbl)
        self._precision_children: Dict[str, object] = {}  # dtype -> child
        self._pipeline_children: Dict[str, object] = {}  # stage -> child
        self._pipeline_bubble = None  # gauge child, set on first publish
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._latencies: deque = deque(maxlen=_RESERVOIR)  # seconds, per request
        # bucket -> [n_batches, total_valid_rows]
        self._occupancy: Dict[int, list] = {}
        # request n_rows -> count: the observed ARRIVAL-size histogram
        # the ladder autotuner plans from (request sizes, not batch
        # sizes — rung spacing must fit what callers actually send)
        self._arrivals: Dict[int, int] = {}
        self._padded_rows = 0   # cumulative bucket rows executed
        self._valid_rows = 0    # cumulative valid rows executed

    # ------------------------------------------------------------------
    def close(self) -> None:
        """Retire this instance's series from the registry exposition
        (snapshot() keeps working off the detached children).  Called by
        InferenceServer.stop() so a process that constructs servers
        repeatedly doesn't grow /metrics without bound."""
        lbl = {"server": self.name, "instance": self.instance}
        for metric in list(_COUNTERS.values()) + [
                _LATENCY, _BATCH_ROWS, _BATCH_VALID_ROWS,
                _LADDER_REPLANS, _PADDING_WASTE]:
            metric.remove_labels(**lbl)
        with self._lock:
            dtypes = list(self._precision_children)
            stages = list(self._pipeline_children)
            had_pipeline = self._pipeline_bubble is not None
        for dtype in dtypes:
            _PRECISION_REQS.remove_labels(dtype=dtype, **lbl)
        for stage in stages:
            _PIPELINE_OCCUPANCY.remove_labels(stage=stage, **lbl)
        if had_pipeline:
            _PIPELINE_BUBBLE.remove_labels(**lbl)

    # ------------------------------------------------------------------
    def count(self, key: str, n: int = 1) -> None:
        self._c[key].inc(n)

    def count_precision(self, dtype: str, n: int = 1) -> None:
        """``n`` requests served by the ``dtype`` compiled variant.
        Child creation is under the instance lock — replica workers
        race the first request of a dtype against snapshot()/close()
        iterating the children."""
        with self._lock:
            child = self._precision_children.get(dtype)
            if child is None:
                child = self._precision_children[dtype] = (
                    _PRECISION_REQS.labels(
                        server=self.name, instance=self.instance,
                        dtype=dtype))
        child.inc(n)

    def count_replan(self) -> None:
        """One applied bucket-ladder re-plan."""
        self._replans.inc()

    def set_pipeline(self, stats: Dict[str, object]) -> None:
        """Publish a pipelined replica's schedule shape (a
        ``PipelinePredictor.pipeline_stats()`` dict): the structural
        bubble ratio plus one occupancy series per stage coordinate."""
        lbl = {"server": self.name, "instance": self.instance}
        with self._lock:
            if self._pipeline_bubble is None:
                self._pipeline_bubble = _PIPELINE_BUBBLE.labels(**lbl)
            bubble = self._pipeline_bubble
            children = []
            for stage, occ in sorted(stats["stage_occupancy"].items()):
                stage = str(stage)
                child = self._pipeline_children.get(stage)
                if child is None:
                    child = self._pipeline_children[stage] = (
                        _PIPELINE_OCCUPANCY.labels(stage=stage, **lbl))
                children.append((child, occ))
        bubble.set(round(float(stats["bubble_ratio"]), 6))
        for child, occ in children:
            child.set(round(float(occ), 6))

    def observe_arrival(self, n_rows: int) -> None:
        """Record one request's row count into the arrival histogram."""
        with self._lock:
            self._arrivals[n_rows] = self._arrivals.get(n_rows, 0) + 1

    def arrival_histogram(self) -> Dict[int, int]:
        """Snapshot of the observed request-size distribution (the
        autotuner's input)."""
        with self._lock:
            return dict(self._arrivals)

    def observe_request(self, latency_s: float,
                        trace_id: Optional[str] = None) -> None:
        self._c["completed"].inc()
        # the exemplar pins THIS request's trace id to the latency
        # bucket it landed in (OpenMetrics exposition) — the bridge from
        # a p99 bucket to the flight recorder / merged trace
        self._latency.observe(
            latency_s,
            exemplar={"trace_id": trace_id} if trace_id else None)
        with self._lock:
            self._latencies.append(latency_s)

    def observe_batch(self, valid: int, bucket: int, run_s: float,
                      recompiled: bool = False,
                      replica: str = None) -> None:
        """Record one executed batch and emit its trace event."""
        self._c["batches"].inc()
        if recompiled:
            self._c["recompiles"].inc()
        self._batch_rows.inc(bucket)
        self._batch_valid.inc(valid)
        with self._lock:
            ent = self._occupancy.setdefault(bucket, [0, 0])
            ent[0] += 1
            ent[1] += valid
            self._padded_rows += bucket
            self._valid_rows += valid
            waste = 1.0 - self._valid_rows / self._padded_rows
        # cumulative padding waste — the measured number the autotuned
        # ladder must strictly reduce (bench_serving reports it)
        self._waste_gauge.set(round(waste, 6))
        event = {
            "event": "serving.batch",
            "server": self.name,
            "valid": int(valid),
            "bucket": int(bucket),
            "run_ms": round(run_s * 1e3, 3),
            "recompiled": bool(recompiled),
        }
        if replica is not None:
            event["replica"] = replica
        profiler.emit_trace_event(event)

    # ------------------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        """Point-in-time metrics dict (the admin/bench surface)."""
        counters = {key: int(c.value) for key, c in self._c.items()}
        with self._lock:
            lats = np.asarray(self._latencies, dtype=np.float64)
            occupancy = {b: tuple(v) for b, v in self._occupancy.items()}
            elapsed = time.perf_counter() - self._t0
            arrivals = dict(self._arrivals)
            padded_rows, valid_rows = self._padded_rows, self._valid_rows
            precision_children = dict(self._precision_children)
        snap: Dict[str, object] = dict(counters)
        snap["elapsed_s"] = round(elapsed, 3)
        snap["qps"] = round(counters["completed"] / elapsed, 2) if elapsed > 0 else 0.0
        if lats.size:
            snap["latency_p50_ms"] = round(float(np.percentile(lats, 50)) * 1e3, 3)
            snap["latency_p99_ms"] = round(float(np.percentile(lats, 99)) * 1e3, 3)
        else:
            snap["latency_p50_ms"] = snap["latency_p99_ms"] = None
        total_rows = sum(b * n for b, (n, _) in occupancy.items())
        total_valid = sum(v for _, v in occupancy.values())
        snap["mean_batch_occupancy"] = (
            round(total_valid / total_rows, 4) if total_rows else None)
        snap["batch_histogram"] = {
            str(b): {"batches": n, "valid_rows": v}
            for b, (n, v) in sorted(occupancy.items())
        }
        snap["arrival_histogram"] = {
            str(k): v for k, v in sorted(arrivals.items())}
        snap["padding_waste_ratio"] = (
            round(1.0 - valid_rows / padded_rows, 4) if padded_rows
            else None)
        snap["ladder_replans"] = int(self._replans.value)
        snap["precision_requests"] = {
            dtype: int(child.value)
            for dtype, child in precision_children.items()}
        return snap
