"""Device-memory observability (reference: paddle/fluid/memory/ —
AllocatorFacade stats, allocation/allocator_facade.h:32, and the
FLAGS_fraction_of_gpu_memory_to_use family, platform/gpu_info.cc).

On TPU, allocation itself belongs to PJRT/XLA (buffer assignment inside
compiled modules, donation at boundaries — see executor.py), so the
framework surface is OBSERVABILITY plus the pre-allocation knobs jax
exposes:

* ``device_memory_stats()`` — live PJRT per-device stats (bytes in use,
  peak, limit) — the `memory::StatGetCurrentValue` analog.
* ``FLAGS_fraction_of_gpu_memory_to_use`` / ``FLAGS_tpu_memory_fraction``
  env var seeds XLA_PYTHON_CLIENT_MEM_FRACTION at import (the gflags→env
  seeding tier, python/__init__.py in the reference).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

__all__ = ["device_memory_stats", "memory_summary"]

# gflags→env seeding (must run before jax initializes its backends)
_frac = os.environ.get("FLAGS_fraction_of_gpu_memory_to_use") or os.environ.get(
    "FLAGS_tpu_memory_fraction"
)
if _frac:
    os.environ.setdefault("XLA_PYTHON_CLIENT_MEM_FRACTION", _frac)


def device_memory_stats(device=None) -> List[Dict[str, Optional[int]]]:
    """Per-device memory stats from PJRT.  Returns a list of dicts with
    ``device``, ``bytes_in_use``, ``peak_bytes_in_use``, ``bytes_limit``
    (None where the platform doesn't report — e.g. CPU)."""
    import jax

    devices = [device] if device is not None else jax.devices()
    out = []
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        stats = stats or {}
        out.append(
            {
                "device": str(d),
                "platform": d.platform,
                "bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
                "num_allocs": stats.get("num_allocs"),
            }
        )
    return out


def memory_summary(device=None) -> str:
    """Human-readable per-device memory table (lodtensor_printer-style
    debug aid)."""
    rows = device_memory_stats(device)
    lines = ["%-28s %14s %14s %14s" % ("device", "in_use", "peak", "limit")]

    def fmt(v):
        if v is None:
            return "-"
        return "%.1fMB" % (v / (1 << 20))

    for r in rows:
        lines.append(
            "%-28s %14s %14s %14s"
            % (r["device"], fmt(r["bytes_in_use"]), fmt(r["peak_bytes_in_use"]), fmt(r["bytes_limit"]))
        )
    return "\n".join(lines)
