"""Weight-decay regularizers appended as grad ops
(reference: python/paddle/fluid/regularizer.py)."""
from __future__ import annotations

from paddle_tpu import framework

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer", "append_regularization_ops"]


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        decay = block.create_var(
            name=grad.name + "@L2DECAY", shape=param.shape, dtype=param.dtype, stop_gradient=True
        )
        block.append_op(
            type="scale",
            inputs={"X": [param]},
            outputs={"Out": [decay]},
            attrs={"scale": self._coeff, "op_role": "backward"},
        )
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        sign = block.create_var(
            name=grad.name + "@L1SIGN", shape=param.shape, dtype=param.dtype, stop_gradient=True
        )
        block.append_op(type="sign", inputs={"X": [param]}, outputs={"Out": [sign]}, attrs={"op_role": "backward"})
        decay = block.create_var(
            name=grad.name + "@L1DECAY", shape=param.shape, dtype=param.dtype, stop_gradient=True
        )
        block.append_op(
            type="scale",
            inputs={"X": [sign]},
            outputs={"Out": [decay]},
            attrs={"scale": self._coeff, "op_role": "backward"},
        )
        return decay


def append_regularization_ops(params_grads, regularization=None):
    """reference: regularizer.py append_regularization_ops — grad += decay."""
    out = []
    for param, grad in params_grads:
        if grad is None:
            out.append((param, grad))
            continue
        reg = param.regularizer if getattr(param, "regularizer", None) is not None else regularization
        if reg is None:
            out.append((param, grad))
            continue
        block = grad.block
        decay = reg(param, grad, block)
        new_grad = block.create_var(
            name=grad.name + "@REGULARIZED", shape=param.shape, dtype=param.dtype, stop_gradient=True
        )
        block.append_op(
            type="sum",
            inputs={"X": [grad, decay]},
            outputs={"Out": [new_grad]},
            attrs={"op_role": "backward"},
        )
        out.append((param, new_grad))
    return out


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
