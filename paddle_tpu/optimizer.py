"""Optimizers — append update ops into the program.

Reference: python/paddle/fluid/optimizer.py:50 (Optimizer base), SGD:609,
Momentum:679, LarsMomentum:1046, Adagrad:1146, Adam:1249, Adamax:1430,
DecayedAdagrad:1584, Adadelta:1676, RMSProp:1774, Ftrl:1947, Lamb:2091.
Optimizer state (moments, beta pows) are persistable vars updated by
optimizer *ops* inside the same compiled XLA module as forward+backward —
the whole train step is one executable (see executor.py docstring).
"""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

import numpy as np

from paddle_tpu import framework, unique_name
from paddle_tpu.backward import append_backward
from paddle_tpu.framework import Parameter, Variable
from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "Optimizer",
    "SGD",
    "SGDOptimizer",
    "Momentum",
    "MomentumOptimizer",
    "LarsMomentum",
    "LarsMomentumOptimizer",
    "Adagrad",
    "AdagradOptimizer",
    "DecayedAdagrad",
    "DecayedAdagradOptimizer",
    "Adam",
    "AdamOptimizer",
    "Adamax",
    "AdamaxOptimizer",
    "Adadelta",
    "AdadeltaOptimizer",
    "RMSProp",
    "RMSPropOptimizer",
    "Ftrl",
    "FtrlOptimizer",
    "Lamb",
    "LambOptimizer",
    "DGCMomentumOptimizer",
    "ModelAverage",
    "ExponentialMovingAverage",
    "PipelineOptimizer",
]


class Optimizer:
    _op_type = None

    def __init__(self, learning_rate, regularization=None, name=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._name = name
        self._accumulators: Dict[str, Dict[str, Variable]] = {}
        self._lr_var: Optional[Variable] = None

    # ------------------------------------------------------------------
    def _create_global_learning_rate(self):
        if isinstance(self._learning_rate, Variable):
            self._lr_var = self._learning_rate
            return
        if callable(self._learning_rate) and not isinstance(
            self._learning_rate, (int, float)
        ):
            # dygraph LearningRateDecay object (dygraph/
            # learning_rate_scheduler.py): calling it returns the current
            # lr AND advances the schedule — eager mode re-creates the lr
            # var each minimize, so the decay steps per call like the
            # reference
            if not framework.in_dygraph_mode():
                raise ValueError(
                    "LearningRateDecay objects are dygraph-only; use "
                    "layers.learning_rate_scheduler in static graphs"
                )
            from paddle_tpu.layers import tensor as ltensor

            self._lr_var = ltensor.create_global_var(
                shape=[1],
                value=float(self._learning_rate()),
                dtype="float32",
                persistable=True,
                name=unique_name.generate("learning_rate"),
            )
            return
        if self._lr_var is not None:
            return
        from paddle_tpu.layers import tensor as ltensor

        self._lr_var = ltensor.create_global_var(
            shape=[1],
            value=float(self._learning_rate),
            dtype="float32",
            persistable=True,
            name=unique_name.generate("learning_rate"),
        )

    def _global_learning_rate(self):
        return self._lr_var

    def _create_param_lr(self, param):
        """Per-param LR multiplier (ParamAttr.learning_rate)."""
        mult = param.optimize_attr.get("learning_rate", 1.0) if param.optimize_attr else 1.0
        if mult == 1.0:
            return self._lr_var
        from paddle_tpu.layers import tensor as ltensor

        return ltensor.scale(self._lr_var, scale=float(mult))

    # ------------------------------------------------------------------
    def _add_accumulator(self, name, param, fill_value=0.0, shape=None, dtype=None):
        if name in self._accumulators and param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        from paddle_tpu import initializer

        helper = LayerHelper(self.__class__.__name__.lower())
        shape = shape if shape is not None else list(param.shape)
        var_name = unique_name.generate("%s_%s" % (param.name, name))
        block = framework.default_main_program().global_block()
        var = block.create_var(
            name=var_name,
            shape=shape,
            dtype=dtype or param.dtype,
            persistable=True,
            stop_gradient=True,
        )
        helper.set_variable_initializer(var, initializer.Constant(fill_value))
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    def accumulator_map(self) -> Dict[str, tuple]:
        """``{accumulator var name: (param name, accumulator kind)}`` for
        every optimizer-state var this optimizer created (populated by
        ``minimize``/``apply_gradients``).  The name↔param surface the
        sharded-training rules consume: each accumulator's placement is
        derived from its param's matched partition rule
        (``paddle_tpu.sharding.train.train_rules``), so the mapping —
        not a name-pattern guess — is the ground truth for which param
        an accumulator belongs to."""
        out: Dict[str, tuple] = {}
        for kind, per_param in self._accumulators.items():
            for pname, var in per_param.items():
                out[var.name] = (pname, kind)
        return out

    # ------------------------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, params_grads):
        pass

    # ------------------------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None, no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        from paddle_tpu import clip as clip_mod
        from paddle_tpu import regularizer as reg_mod

        block = framework.default_main_program().global_block()
        self._create_global_learning_rate()
        params_grads = clip_mod.append_gradient_clip_ops(params_grads)
        params_grads = reg_mod.append_regularization_ops(params_grads, self.regularization)
        self._create_accumulators(block, [p for p, _ in params_grads])
        ops = []
        for pg in params_grads:
            if pg[1] is None:
                continue
            ops.append(self._append_optimize_op(block, pg))
        self._finish_update(block, params_grads)
        block.program.version += 1
        return ops

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        if framework.in_dygraph_mode():
            return self._dygraph_minimize(loss, parameter_list)
        params_grads = self.backward(loss, startup_program, parameter_list, no_grad_set)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    def _dygraph_minimize(self, loss, parameter_list=None):
        """Eager update: grads were attached by loss.backward(); run the
        optimizer ops through the tracer (reference: dygraph branch of
        optimizer.minimize)."""
        from paddle_tpu.dygraph import base as dybase

        tracer = framework._dygraph_tracer()
        params = parameter_list
        if params is None:
            seen = {}
            for entry in tracer.tape:
                for vs in entry.inputs.values():
                    for v in vs:
                        if isinstance(v, Parameter) and getattr(v, "_dy_grad", None) is not None:
                            seen[id(v)] = v
            params = list(seen.values())
        pgs = []
        block = framework.default_main_program().global_block()
        for p in params:
            g = getattr(p, "_dy_grad", None)
            if g is None:
                continue
            gv = framework.Variable(
                block, unique_name.generate(p.name + "@GRAD"),
                shape=tuple(np.shape(g)), dtype=p.dtype, stop_gradient=True,
            )
            gv._dy_value = g
            pgs.append((p, gv))
        with dybase.no_grad():
            self.apply_gradients(pgs)
        tracer.reset()
        return None, pgs


# ---------------------------------------------------------------------------
class SGDOptimizer(Optimizer):
    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="sgd",
            inputs={"Param": [p], "Grad": [g], "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p]},
            attrs={"op_role": "optimize"},
        )


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            type="momentum",
            inputs={"Param": [p], "Grad": [g], "Velocity": [v], "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p], "VelocityOut": [v]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov, "op_role": "optimize"},
        )


class LarsMomentumOptimizer(MomentumOptimizer):
    def __init__(self, learning_rate, momentum, lars_coeff=0.001, lars_weight_decay=0.0005, **kwargs):
        super().__init__(learning_rate, momentum, **kwargs)
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            type="lars_momentum",
            inputs={"Param": [p], "Grad": [g], "Velocity": [v], "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p], "VelocityOut": [v]},
            attrs={
                "mu": self._momentum,
                "lars_coeff": self._lars_coeff,
                "lars_weight_decay": self._lars_weight_decay,
                "op_role": "optimize",
            },
        )


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._init_acc)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        return block.append_op(
            type="adagrad",
            inputs={"Param": [p], "Grad": [g], "Moment": [m], "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p], "MomentOut": [m]},
            attrs={"epsilon": self._epsilon, "op_role": "optimize"},
        )


class DecayedAdagradOptimizer(AdagradOptimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, epsilon=epsilon, **kwargs)
        self._decay = decay

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        return block.append_op(
            type="decayed_adagrad",
            inputs={"Param": [p], "Grad": [g], "Moment": [m], "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p], "MomentOut": [m]},
            attrs={"epsilon": self._epsilon, "decay": self._decay, "op_role": "optimize"},
        )


class AdamOptimizer(Optimizer):
    _op = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, lazy_mode=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        return block.append_op(
            type=self._op,
            inputs={
                "Param": [p],
                "Grad": [g],
                "Moment1": [m1],
                "Moment2": [m2],
                "Beta1Pow": [b1p],
                "Beta2Pow": [b2p],
                "LearningRate": [self._create_param_lr(p)],
            },
            outputs={
                "ParamOut": [p],
                "Moment1Out": [m1],
                "Moment2Out": [m2],
                "Beta1PowOut": [b1p],
                "Beta2PowOut": [b2p],
            },
            attrs={
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
                "op_role": "optimize",
            },
        )


class LambOptimizer(AdamOptimizer):
    _op = "lamb"

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, beta1, beta2, epsilon, **kwargs)
        self._weight_decay = lamb_weight_decay

    def _append_optimize_op(self, block, param_and_grad):
        op = super()._append_optimize_op(block, param_and_grad)
        op.attrs["weight_decay"] = self._weight_decay
        return op


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="adamax",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Moment": [self._get_accumulator("moment", p)],
                "InfNorm": [self._get_accumulator("inf_norm", p)],
                "Beta1Pow": [self._get_accumulator("beta1_pow_acc", p)],
                "LearningRate": [self._create_param_lr(p)],
            },
            outputs={
                "ParamOut": [p],
                "MomentOut": [self._get_accumulator("moment", p)],
                "InfNormOut": [self._get_accumulator("inf_norm", p)],
            },
            attrs={"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon, "op_role": "optimize"},
        )

    def _finish_update(self, block, params_grads):
        # beta1 pow update (reference: optimizer.py Adamax._finish_update)
        for p, _ in params_grads:
            b1p = self._get_accumulator("beta1_pow_acc", p)
            block.append_op(
                type="scale",
                inputs={"X": [b1p]},
                outputs={"Out": [b1p]},
                attrs={"scale": self._beta1, "op_role": "optimize"},
            )


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("__avg_squared_grad", p)
            self._add_accumulator("__avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        asg = self._get_accumulator("__avg_squared_grad", p)
        asu = self._get_accumulator("__avg_squared_update", p)
        return block.append_op(
            type="adadelta",
            inputs={"Param": [p], "Grad": [g], "AvgSquaredGrad": [asg], "AvgSquaredUpdate": [asu]},
            outputs={"ParamOut": [p], "AvgSquaredGradOut": [asg], "AvgSquaredUpdateOut": [asu]},
            attrs={"epsilon": self._epsilon, "rho": self._rho, "op_role": "optimize"},
        )


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._rho, self._epsilon, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="rmsprop",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Moment": [self._get_accumulator("momentum", p)],
                "MeanSquare": [self._get_accumulator("mean_square", p)],
                "MeanGrad": [self._get_accumulator("mean_grad", p)],
                "LearningRate": [self._create_param_lr(p)],
            },
            outputs={
                "ParamOut": [p],
                "MomentOut": [self._get_accumulator("momentum", p)],
                "MeanSquareOut": [self._get_accumulator("mean_square", p)],
                "MeanGradOut": [self._get_accumulator("mean_grad", p)],
            },
            attrs={
                "decay": self._rho,
                "epsilon": self._epsilon,
                "momentum": self._momentum,
                "centered": self._centered,
                "op_role": "optimize",
            },
        )


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="ftrl",
            inputs={
                "Param": [p],
                "Grad": [g],
                "SquaredAccumulator": [self._get_accumulator("squared", p)],
                "LinearAccumulator": [self._get_accumulator("linear", p)],
                "LearningRate": [self._create_param_lr(p)],
            },
            outputs={
                "ParamOut": [p],
                "SquaredAccumOut": [self._get_accumulator("squared", p)],
                "LinearAccumOut": [self._get_accumulator("linear", p)],
            },
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power, "op_role": "optimize"},
        )


class DGCMomentumOptimizer(MomentumOptimizer):
    """Deep Gradient Compression (reference: optimizer.py:787 +
    operators/dgc_op.cc + details/sparse_all_reduce_op_handle.h:30).

    Appends a real ``dgc_momentum`` op per parameter: local momentum
    correction (u = mu*u + g), gradient accumulation (v += u), top-k
    sparsification on |v| with accumulator clearing at selected
    positions, dense phase before ``rampup_begin_step``, and allreduce of
    the sparse tensor over the active dp axis.  ``sparsity`` takes the
    FINAL value of the reference's schedule (XLA needs a static k).
    """

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 rampup_step=1, sparsity=None, **kwargs):
        super().__init__(learning_rate, momentum, **kwargs)
        self._rampup_begin_step = float(rampup_begin_step)
        self._sparsity = float((sparsity or [0.999])[-1])
        if rampup_step != 1 or (sparsity is not None and len(sparsity) > 1):
            import warnings

            warnings.warn(
                "DGCMomentumOptimizer uses the FINAL sparsity (%.4f) from "
                "step rampup_begin_step on: the reference's gradual "
                "rampup_step schedule needs per-stage static k values XLA "
                "would recompile for, so it is not applied"
                % self._sparsity,
                stacklevel=2,
            )
        self._dgc_step_var = None

    def _create_accumulators(self, block, parameters):
        from paddle_tpu import initializer

        helper = LayerHelper("dgc_momentum")
        for p in parameters:
            self._add_accumulator("dgc_u", p)
            self._add_accumulator("dgc_v", p)
        if self._dgc_step_var is None:
            self._dgc_step_var = block.create_var(
                name=unique_name.generate("@DGC_STEP@"),
                shape=[1], dtype="float32", persistable=True, stop_gradient=True,
            )
            helper.set_variable_initializer(self._dgc_step_var, initializer.Constant(0.0))

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="dgc_momentum",
            inputs={
                "Param": [p], "Grad": [g],
                "U": [self._get_accumulator("dgc_u", p)],
                "V": [self._get_accumulator("dgc_v", p)],
                "CurrentStep": [self._dgc_step_var],
                "LearningRate": [self._create_param_lr(p)],
            },
            outputs={
                "ParamOut": [p],
                "UOut": [self._get_accumulator("dgc_u", p)],
                "VOut": [self._get_accumulator("dgc_v", p)],
            },
            attrs={"mu": self._momentum, "sparsity": self._sparsity,
                   "rampup_begin_step": self._rampup_begin_step,
                   "op_role": "optimize"},
        )

    def _finish_update(self, block, params_grads):
        block.append_op(
            type="scale",
            inputs={"X": [self._dgc_step_var]},
            outputs={"Out": [self._dgc_step_var]},
            attrs={"scale": 1.0, "bias": 1.0, "op_role": "optimize"},
        )


class ModelAverage:
    """Sliding-window parameter average for evaluation (reference:
    optimizer.py:2245 + operators/average_accumulates_op.cc).

    Construction appends one ``average_accumulates`` op per parameter —
    the reference's sum_1/sum_2/sum_3 windowed accumulators with restart
    logic, fused into the compiled step.  ``apply`` swaps
    (sum_1+sum_2+sum_3)/(num_accumulates+old_num_accumulates) into the
    scope host-side (the reference builds tiny swap programs; on TPU a
    host swap of HBM handles is equivalent).
    """

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None):
        self.average_window_rate = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        block = framework.default_main_program().global_block()
        helper = LayerHelper("model_average")
        self._params = [p for p in block.all_parameters() if getattr(p, "trainable", True)]
        self._accs = {}
        from paddle_tpu import initializer

        def _state(name, shape, dtype="float32"):
            v = block.create_var(
                name=unique_name.generate(name), shape=shape, dtype=dtype,
                persistable=True, stop_gradient=True,
            )
            helper.set_variable_initializer(v, initializer.Constant(0.0))
            return v

        for p in self._params:
            s1 = _state(p.name + "@MA_SUM1@", p.shape)
            s2 = _state(p.name + "@MA_SUM2@", p.shape)
            s3 = _state(p.name + "@MA_SUM3@", p.shape)
            na = _state(p.name + "@MA_NACC@", [1], "int64")
            no = _state(p.name + "@MA_OLDN@", [1], "int64")
            nu = _state(p.name + "@MA_NUPD@", [1], "int64")
            block.append_op(
                type="average_accumulates",
                inputs={"Param": [p.name], "Sum1": [s1.name], "Sum2": [s2.name],
                        "Sum3": [s3.name], "NumAccumulates": [na.name],
                        "OldNumAccumulates": [no.name], "NumUpdates": [nu.name]},
                outputs={"Sum1Out": [s1.name], "Sum2Out": [s2.name],
                         "Sum3Out": [s3.name], "NumAccumulatesOut": [na.name],
                         "OldNumAccumulatesOut": [no.name], "NumUpdatesOut": [nu.name]},
                attrs={"average_window": self.average_window_rate,
                       "min_average_window": self.min_average_window,
                       "max_average_window": self.max_average_window,
                       "op_role": "optimize"},
            )
            self._accs[p.name] = (s1, s2, s3, na, no)
        block.program.version += 1
        self._backup = None

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        import jax.numpy as jnp

        from paddle_tpu.scope import global_scope

        scope = global_scope()
        self._backup = {}
        for p in self._params:
            s1, s2, s3, na, no = self._accs[p.name]
            total = np.asarray(scope.get(na.name)).item() + np.asarray(
                scope.get(no.name)
            ).item()
            total = max(total, 1.0)
            self._backup[p.name] = scope.get(p.name)
            avg = (
                jnp.asarray(scope.get(s1.name))
                + jnp.asarray(scope.get(s2.name))
                + jnp.asarray(scope.get(s3.name))
            ) / total
            scope.set(p.name, avg.astype(self._backup[p.name].dtype))
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor=None):
        from paddle_tpu.scope import global_scope

        if self._backup:
            scope = global_scope()
            for name, val in self._backup.items():
                scope.set(name, val)
            self._backup = None


class ExponentialMovingAverage:
    """EMA of parameters (reference: optimizer.py:2435).

    ``update()`` appends the in-graph decay ops plus a step counter and a
    decay-power accumulator; ``apply()`` installs the *bias-corrected*
    EMA — ema / (1 - prod(decay_t)) — matching the reference's
    ``_ema_vars[...] / (1 - decay_pow)`` apply-time correction, so early
    evaluations are not biased toward the zero initialization.
    ``thres_steps`` schedules the decay as
    min(decay, (1 + step) / (10 + step)) like the reference.
    """

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._thres_steps = thres_steps
        self._ema = {}
        self._params = []
        self._backup = None
        self._step_var = None
        self._dpow_var = None

    def update(self):
        """Append ema = decay_t*ema + (1-decay_t)*param for every
        trainable param in the default main program (call after
        minimize)."""
        from paddle_tpu import initializer

        block = framework.default_main_program().global_block()
        helper = LayerHelper("ema")
        self._params = [p for p in block.all_parameters() if getattr(p, "trainable", True)]

        def _state(name, init):
            v = block.create_var(
                name=unique_name.generate(name), shape=[1], dtype="float32",
                persistable=True, stop_gradient=True,
            )
            helper.set_variable_initializer(v, initializer.Constant(init))
            return v

        def _tmp(name, shape=(1,), dtype="float32"):
            return block.create_var(
                name=unique_name.generate(name), shape=list(shape), dtype=dtype
            )

        def _op(type, ins, outs, **attrs):
            attrs.setdefault("op_role", "optimize")
            block.append_op(type=type, inputs=ins, outputs=outs, attrs=attrs)

        if self._step_var is None:
            self._step_var = _state("@EMA_STEP@", 0.0)
            self._dpow_var = _state("@EMA_DPOW@", 1.0)
            _op("scale", {"X": [self._step_var.name]}, {"Out": [self._step_var.name]},
                scale=1.0, bias=1.0)
            # decay_t: scheduled min(decay, (1+t)/(10+t)) or constant.
            # thres_steps may be the user's global-step Variable
            # (reference API) — drive the schedule from it; any other
            # truthy value falls back to the internal step counter.
            decay_t = _tmp("@EMA_DECAY@")
            if self._thres_steps is not None:
                if isinstance(self._thres_steps, framework.Variable):
                    step_src = _tmp("@EMA_TSRC@")
                    _op("cast", {"X": [self._thres_steps.name]}, {"Out": [step_src.name]},
                        in_dtype=self._thres_steps.dtype, out_dtype="float32")
                    step_name = step_src.name
                else:
                    step_name = self._step_var.name
                num = _tmp("@EMA_NUM@")
                den = _tmp("@EMA_DEN@")
                cst = _tmp("@EMA_CST@")
                _op("scale", {"X": [step_name]}, {"Out": [num.name]},
                    scale=1.0, bias=1.0)
                _op("scale", {"X": [step_name]}, {"Out": [den.name]},
                    scale=1.0, bias=10.0)
                _op("elementwise_div", {"X": [num.name], "Y": [den.name]}, {"Out": [cst.name]})
                sched = _tmp("@EMA_SCHED@")
                _op("scale", {"X": [self._step_var.name]}, {"Out": [sched.name]},
                    scale=0.0, bias=self._decay)
                _op("elementwise_min", {"X": [cst.name], "Y": [sched.name]},
                    {"Out": [decay_t.name]})
            else:
                _op("scale", {"X": [self._step_var.name]}, {"Out": [decay_t.name]},
                    scale=0.0, bias=self._decay)
            _op("elementwise_mul", {"X": [self._dpow_var.name], "Y": [decay_t.name]},
                {"Out": [self._dpow_var.name]})
            self._decay_var = decay_t

        one_minus = _tmp("@EMA_1MD@")
        _op("scale", {"X": [self._decay_var.name]}, {"Out": [one_minus.name]},
            scale=-1.0, bias=1.0)
        for p in self._params:
            if p.name in self._ema:
                continue
            e = block.create_var(
                name=unique_name.generate(p.name + "@EMA@"),
                shape=p.shape, dtype=p.dtype, persistable=True, stop_gradient=True,
            )
            helper.set_variable_initializer(e, initializer.Constant(0.0))
            scaled_e = _tmp(p.name + "@EMA_T@", p.shape, p.dtype)
            scaled_p = _tmp(p.name + "@EMA_P@", p.shape, p.dtype)
            _op("elementwise_mul", {"X": [e.name], "Y": [self._decay_var.name]},
                {"Out": [scaled_e.name]})
            _op("elementwise_mul", {"X": [p.name], "Y": [one_minus.name]},
                {"Out": [scaled_p.name]})
            _op("elementwise_add", {"X": [scaled_e.name], "Y": [scaled_p.name]},
                {"Out": [e.name]})
            self._ema[p.name] = e
        block.program.version += 1

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        import jax.numpy as jnp

        from paddle_tpu.scope import global_scope

        scope = global_scope()
        self._backup = {}
        dpow = (
            np.asarray(scope.get(self._dpow_var.name)).item()
            if self._dpow_var is not None
            else 0.0
        )
        corr = max(1.0 - dpow, 1e-12)
        for p in self._params:
            self._backup[p.name] = scope.get(p.name)
            ema = jnp.asarray(scope.get(self._ema[p.name].name))
            scope.set(p.name, (ema / corr).astype(self._backup[p.name].dtype))
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor=None):
        from paddle_tpu.scope import global_scope

        if self._backup:
            scope = global_scope()
            for name, val in self._backup.items():
                scope.set(name, val)
            self._backup = None


class PipelineOptimizer:
    """Pipeline-parallel optimizer (reference: optimizer.py:2665 — cuts
    the program into sections run by SectionWorker threads,
    framework/pipeline_trainer.cc, section_worker.cc:141).

    TPU-native: with a non-empty ``cut_list`` the program's forward is
    cut into stages and the executor runs a COMPILED GPipe schedule over
    the ``pp`` mesh axis (parallel/pipeline_program.py — ppermute ring
    inside one lax.scan; reverse-mode AD through it is the reference's
    2K-1 backward sections).  The wrapped optimizer's update rule is
    applied functionally; sgd and momentum are supported — for other
    optimizers or stage-sharded memory scaling use the hybrid engine
    (parallel/hybrid.py).

    Without a cut_list this degrades to the wrapped optimizer plus a
    recorded microbatch plan (API-parity surface).
    """

    def __init__(self, optimizer, cut_list=None, place_list=None, concurrency_list=None,
                 queue_size=30, sync_steps=1, start_cpu_core_id=0, num_microbatches=None):
        self._optimizer = optimizer
        self._cut_list = cut_list or []
        self._num_microbatches = num_microbatches or max(1, len(self._cut_list) or 1)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        prog = loss.block.program
        if self._cut_list:
            opt = self._optimizer
            # program-level regularization ops land on the grad side,
            # which the AD-replay schedule skips; record the decay rule
            # per param and apply it functionally in the replay
            # (parallel/pipeline_program.py local_step)
            def _decay_rule(reg):
                from paddle_tpu import regularizer as reg_mod

                if reg is None:
                    return None
                if isinstance(reg, reg_mod.L2DecayRegularizer):
                    return ("l2", float(reg._coeff))
                if isinstance(reg, reg_mod.L1DecayRegularizer):
                    return ("l1", float(reg._coeff))
                raise NotImplementedError(
                    "pipeline path supports L1/L2 decay regularization "
                    "(got %s)" % type(reg).__name__
                )

            decay_map = {}
            for p in prog.all_parameters():
                rule = _decay_rule(
                    getattr(p, "regularizer", None) or opt.regularization
                )
                if rule is not None and p.trainable:
                    decay_map[p.name] = rule
            if parameter_list is not None or no_grad_set:
                raise NotImplementedError("pipeline path updates all trainable params")
            for p in prog.all_parameters():
                if p.optimize_attr and p.optimize_attr.get("learning_rate", 1.0) != 1.0:
                    raise NotImplementedError(
                        "pipeline path ignores per-param LR multipliers (%s)" % p.name
                    )
            # run the wrapped optimizer for real: its update ops land in
            # the program (op_role=optimize) and its accumulators get
            # startup initializers.  The compiled schedule skips the
            # appended backward ops (AD through the scan replaces them,
            # the reference's 2K-1 backward sections) and REPLAYS the
            # update ops' registered kernels on the functional state —
            # any optimizer in sections (reference: optimizer.py:2665).
            ops, params_grads = opt.minimize(
                loss, startup_program, parameter_list, no_grad_set
            )
            block = prog.global_block()
            update_descs = []
            for op in block.ops:
                if (
                    op.attrs.get("op_role") == "optimize"
                    and "Param" in op.inputs
                    and "Grad" in op.inputs
                ):
                    update_descs.append({
                        "type": op.type,
                        "inputs": {s: list(ns) for s, ns in op.inputs.items()},
                        "outputs": {s: list(ns) for s, ns in op.outputs.items()},
                        "attrs": {k: v for k, v in op.attrs.items()
                                  if not k.startswith("__")},
                    })
            if not update_descs:
                raise NotImplementedError(
                    "PipelineOptimizer: wrapped optimizer %r appended no "
                    "Param/Grad update ops" % type(opt).__name__
                )
            prog._pipeline_plan = {
                "cut_vars": [getattr(v, "name", v) for v in self._cut_list],
                "num_microbatches": self._num_microbatches,
                "loss_name": loss.name,
                "update_descs": update_descs,
                "decay": decay_map,
            }
            return ops, params_grads
        ops, pgs = self._optimizer.minimize(loss, startup_program, parameter_list, no_grad_set)
        prog._pipeline_config = {
            "num_microbatches": self._num_microbatches,
            "cut_vars": [],
        }
        return ops, pgs


SGD = SGDOptimizer
Momentum = MomentumOptimizer
LarsMomentum = LarsMomentumOptimizer
Adagrad = AdagradOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
