"""Chrome trace-event exporter (chrome://tracing / Perfetto).

The TPU-native analog of the reference's ``timeline.py`` (which merged
host RecordEvent profiles with CUPTI device records into one trace
file): this merges

* recorded host spans (``monitor.spans`` — Executor run phases,
  lowering, RecordEvent blocks, serving batches), and
* the profiler's JSONL event stream (``profiler.emit_trace_event`` —
  discrete events like ``serving.batch`` with a wall ``ts`` and
  optionally a ``run_ms`` duration)

into a single ``trace.json`` in the trace-event format.  Device-side
XLA traces stay in jax.profiler/xprof (XPlane); this file is the
host-side story, viewable alongside it.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Sequence

__all__ = ["export_chrome_trace"]


def _jsonl_events(path: str) -> List[Dict[str, object]]:
    events = []
    try:
        f = open(path)
    except OSError:
        # the sink may never have been started (e.g. the traced body
        # failed early) — an absent stream must not kill the export
        return events
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                continue  # a torn tail line must not kill the export
    return events


def export_chrome_trace(
    path: str,
    spans: Optional[Sequence[Dict[str, object]]] = None,
    jsonl_path: Optional[str] = None,
    pid: Optional[int] = None,
) -> str:
    """Write ``path`` as a chrome://tracing-loadable JSON object.

    ``spans``: output of ``spans.stop_recording()`` (or any list in that
    shape).  ``jsonl_path``: an ``emit_trace_event`` JSONL file to merge.
    Timestamps from both sources share the wall-clock timebase; the
    earliest event is rebased to t=0 so the viewer opens centered.
    """
    spans = list(spans or [])
    jsonl = _jsonl_events(jsonl_path) if jsonl_path else []
    pid = os.getpid() if pid is None else pid

    starts = [float(s["ts"]) for s in spans]
    for ev in jsonl:
        ts = float(ev.get("ts", 0.0))
        starts.append(ts - float(ev.get("run_ms", 0.0)) / 1e3)
    base = min(starts) if starts else 0.0

    events: List[Dict[str, object]] = []
    tids = set()
    for s in spans:
        tid = int(s.get("tid", 0))
        tids.add(tid)
        args = dict(s.get("args") or {})
        if s.get("error"):
            args["error"] = True
        ev = {
            "name": str(s["name"]),
            "cat": str(s.get("cat", "host")),
            "ph": "X",
            "ts": (float(s["ts"]) - base) * 1e6,  # microseconds
            "dur": float(s.get("dur", 0.0)) * 1e6,
            "pid": pid,
            "tid": tid,
        }
        if args.pop("instant", None):
            ev["ph"] = "i"
            ev["s"] = "t"
            ev.pop("dur")
        if args:
            ev["args"] = args
        events.append(ev)

    _JSONL_TID = 0  # dedicated lane for the discrete event stream
    for rec in jsonl:
        rec = dict(rec)
        name = str(rec.pop("event", "event"))
        ts = float(rec.pop("ts", base))
        run_ms = rec.pop("run_ms", None)
        ev = {
            "name": name,
            "cat": "jsonl",
            "pid": pid,
            "tid": _JSONL_TID,
        }
        if run_ms is not None:
            # ts was stamped at emit time (batch END) — rebase to start
            ev["ph"] = "X"
            ev["ts"] = (ts - float(run_ms) / 1e3 - base) * 1e6
            ev["dur"] = float(run_ms) * 1e3
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
            ev["ts"] = (ts - base) * 1e6
        if rec:
            ev["args"] = rec
        events.append(ev)

    meta: List[Dict[str, object]] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "paddle_tpu host"},
    }]
    if jsonl:
        meta.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": _JSONL_TID,
            "args": {"name": "jsonl events"},
        })
    main_tid = threading.get_ident()
    for tid in sorted(tids):
        meta.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": "main" if tid == main_tid else "thread-%d" % tid},
        })

    events.sort(key=lambda e: e.get("ts", 0.0))
    with open(path, "w") as f:
        json.dump({"traceEvents": meta + events, "displayTimeUnit": "ms"}, f)
    return path
