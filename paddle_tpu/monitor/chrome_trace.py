"""Chrome trace-event exporter (chrome://tracing / Perfetto).

The TPU-native analog of the reference's ``timeline.py`` (which merged
host RecordEvent profiles with CUPTI device records into one trace
file): this merges

* recorded host spans (``monitor.spans`` — Executor run phases,
  lowering, RecordEvent blocks, serving batches, each carrying its
  request ``trace_ids`` when recorded under a trace context),
* the profiler's JSONL event stream (``profiler.emit_trace_event`` —
  discrete events like ``serving.batch`` with a wall ``ts`` and
  optionally a ``run_ms`` duration),
* flight-recorder records (``requests=`` — tail-sampled slow/errored
  request span trees), and
* a ``jax.profiler`` trace directory (``device_trace_dir=`` — the
  profiler's exported trace-event JSON, XPlane-derived), time-aligned
  with the host spans,

into a single ``trace.json`` in the trace-event format — client span,
queue wait, batch assembly, executor h2d/execute/d2h, and the
device-side XLA timeline on one scroll, attributable to one trace id.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import threading
from typing import Dict, List, Optional, Sequence

from paddle_tpu.monitor import spans as _spans

__all__ = ["export_chrome_trace"]

# device-trace events keep their own pid topology (one pid per XLA
# process/planes group), offset into a reserved range so they can never
# collide with the exporting host process's pid
_DEVICE_PID_BASE = 1 << 20


def _jsonl_events(path: str) -> List[Dict[str, object]]:
    events = []
    try:
        f = open(path)
    except OSError:
        # the sink may never have been started (e.g. the traced body
        # failed early) — an absent stream must not kill the export
        return events
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                continue  # a torn tail line must not kill the export
    return events


def _load_device_trace(trace_dir: str) -> List[Dict[str, object]]:
    """Load trace events from a ``jax.profiler.start_trace`` log dir.

    The profiler writes ``plugins/profile/<run>/`` containing the
    XPlane proto plus its exported trace-event JSON
    (``<host>.trace.json.gz``; ``perfetto_trace.json.gz`` when the
    trace was started with ``create_perfetto_trace=True``).  The newest
    run wins; a dir with no exported JSON yields [] (never raises — a
    half-written profile must not kill the host-side export)."""
    roots = [trace_dir]
    profile_root = os.path.join(trace_dir, "plugins", "profile")
    if os.path.isdir(profile_root):
        runs = sorted(
            d for d in glob.glob(os.path.join(profile_root, "*"))
            if os.path.isdir(d))
        roots = runs[-1:] + roots
    for root in roots:
        candidates = (
            sorted(glob.glob(os.path.join(root, "*.trace.json.gz")))
            + sorted(glob.glob(os.path.join(root, "perfetto_trace.json.gz")))
            + sorted(glob.glob(os.path.join(root, "*.trace.json"))))
        for cand in candidates:
            try:
                opener = gzip.open if cand.endswith(".gz") else open
                with opener(cand, "rt") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            evs = doc.get("traceEvents") if isinstance(doc, dict) else doc
            if isinstance(evs, list) and evs:
                return evs
    return []


def _device_anchor_default(trace_dir: str) -> Optional[float]:
    """Wall-clock seconds at device-trace t=0, from the profiler's own
    bookkeeping when this process started the trace."""
    try:
        from paddle_tpu import profiler

        last = profiler.last_device_trace()
    except Exception:
        return None
    if last and os.path.abspath(last[0]) == os.path.abspath(trace_dir):
        return last[1]
    return None


def export_chrome_trace(
    path: str,
    spans: Optional[Sequence[Dict[str, object]]] = None,
    jsonl_path: Optional[str] = None,
    pid: Optional[int] = None,
    requests: Optional[Sequence[Dict[str, object]]] = None,
    device_trace_dir: Optional[str] = None,
    device_anchor: Optional[float] = None,
) -> str:
    """Write ``path`` as a chrome://tracing-loadable JSON object.

    ``spans``: output of ``spans.stop_recording()`` (or any list in that
    shape).  ``jsonl_path``: an ``emit_trace_event`` JSONL file to merge.
    ``requests``: flight-recorder records (``FlightRecorder.snapshot()``)
    whose span trees are merged in with their trace ids.
    ``device_trace_dir``: a ``jax.profiler`` log dir whose exported
    trace-event JSON is merged as device-side lanes; ``device_anchor``
    is the wall-clock time at device-trace t=0 (defaulting to the
    profiler module's recorded start time for that dir, else aligned to
    the earliest host event).  Timestamps from every source share the
    wall-clock timebase; the earliest event is rebased to t=0 so the
    viewer opens centered.
    """
    spans = list(spans or [])
    for rec in requests or ():
        spans.extend(rec.get("spans") or ())
    jsonl = _jsonl_events(jsonl_path) if jsonl_path else []
    device = _load_device_trace(device_trace_dir) if device_trace_dir else []
    pid = os.getpid() if pid is None else pid

    starts = [float(s["ts"]) for s in spans if "ts" in s]
    for ev in jsonl:
        ts = float(ev.get("ts", 0.0))
        starts.append(ts - float(ev.get("run_ms", 0.0)) / 1e3)
    if device:
        if device_anchor is None:
            device_anchor = _device_anchor_default(device_trace_dir)
        if device_anchor is not None:
            starts.append(device_anchor)
    base = min(starts) if starts else 0.0
    if device and device_anchor is None:
        device_anchor = base  # no anchor known: device t=0 at first host event

    events: List[Dict[str, object]] = []
    tids = set()
    # span-id -> (ts_us, tid) of every exported span: parent edges that
    # cross lanes (threads, or processes via a wire hop) get explicit
    # flow arrows — the viewer draws the hierarchy instead of the reader
    # inferring it from timestamps
    span_sites: Dict[str, tuple] = {}
    child_edges: List[tuple] = []  # (child_id, parent_id, ts_us, tid)
    for s in spans:
        if "ts" not in s:
            continue  # a torn/foreign span dict must not kill the export
        tid = int(s.get("tid", 0))
        tids.add(tid)
        args = dict(s.get("args") or {})
        if s.get("error"):
            args["error"] = True
        if s.get("trace_ids"):
            args["trace_ids"] = list(s["trace_ids"])
        ev = {
            "name": str(s["name"]),
            "cat": str(s.get("cat", "host")),
            "ph": "X",
            "ts": (float(s["ts"]) - base) * 1e6,  # microseconds
            "dur": float(s.get("dur", 0.0)) * 1e6,
            "pid": pid,
            "tid": tid,
        }
        if s.get("id"):
            args["span_id"] = s["id"]
            span_sites[str(s["id"])] = (ev["ts"], tid)
        if s.get("parent"):
            args["parent_id"] = s["parent"]
            if s.get("id"):
                child_edges.append(
                    (str(s["id"]), str(s["parent"]), ev["ts"], tid))
        if args.pop("instant", None):
            ev["ph"] = "i"
            ev["s"] = "t"
            ev.pop("dur")
        if args:
            ev["args"] = args
        events.append(ev)

    for child_id, parent_id, ts_us, tid in child_edges:
        site = span_sites.get(parent_id)
        if site is None or site[1] == tid:
            continue  # same lane (nesting is visible) or parent not exported
        try:
            flow_id = int(child_id, 16) & 0x7FFFFFFF
        except ValueError:
            continue  # foreign span dict with a non-hex id
        events.append({
            "name": "span_parent", "cat": "flow", "ph": "s",
            "ts": site[0], "pid": pid, "tid": site[1], "id": flow_id,
        })
        events.append({
            "name": "span_parent", "cat": "flow", "ph": "f", "bp": "e",
            "ts": max(ts_us, site[0]), "pid": pid, "tid": tid, "id": flow_id,
        })

    _JSONL_TID = 0  # dedicated lane for the discrete event stream
    for rec in jsonl:
        rec = dict(rec)
        name = str(rec.pop("event", "event"))
        ts = float(rec.pop("ts", base))
        run_ms = rec.pop("run_ms", None)
        ev = {
            "name": name,
            "cat": "jsonl",
            "pid": pid,
            "tid": _JSONL_TID,
        }
        if run_ms is not None:
            # ts was stamped at emit time (batch END) — rebase to start
            ev["ph"] = "X"
            ev["ts"] = (ts - float(run_ms) / 1e3 - base) * 1e6
            ev["dur"] = float(run_ms) * 1e3
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
            ev["ts"] = (ts - base) * 1e6
        if rec:
            ev["args"] = rec
        events.append(ev)

    # device-side lanes: the profiler's events are already trace-event
    # dicts with µs timestamps relative to its session start — shift
    # them onto the shared wall timebase and move their pids into the
    # reserved device range (metadata rows ride along so Perfetto shows
    # the XLA process/thread names)
    device_meta: List[Dict[str, object]] = []
    device_shift_us = ((device_anchor or 0.0) - base) * 1e6
    for ev in device:
        if not isinstance(ev, dict) or "ph" not in ev:
            continue
        ev = dict(ev)
        if "pid" in ev:
            try:
                ev["pid"] = _DEVICE_PID_BASE + int(ev["pid"])
            except (TypeError, ValueError):
                continue
        if ev.get("ph") == "M":
            device_meta.append(ev)
            continue
        if "ts" not in ev:
            continue
        ev["ts"] = float(ev["ts"]) + device_shift_us
        ev["cat"] = ev.get("cat", "device")
        events.append(ev)

    meta: List[Dict[str, object]] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "paddle_tpu host"},
    }]
    if jsonl:
        meta.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": _JSONL_TID,
            "args": {"name": "jsonl events"},
        })
    main_tid = threading.get_ident()
    lanes = _spans.thread_lanes()
    # every REGISTERED lane gets its name row even when its thread
    # recorded no span this session (an idle replica worker is still a
    # track the fleet view should name; viewers ignore eventless tids)
    for tid in sorted(tids | set(lanes)):
        name = lanes.get(tid) or (
            "main" if tid == main_tid else "thread-%d" % tid)
        meta.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": name},
        })
    meta.extend(device_meta)

    events.sort(key=lambda e: e.get("ts", 0.0))
    with open(path, "w") as f:
        json.dump({"traceEvents": meta + events, "displayTimeUnit": "ms"}, f)
    return path
