"""Host-side span recording for run-phase tracing.

Instrumentation sites (Executor.run phases, lowering, RecordEvent) call
``record_span`` unconditionally; it is a no-op unless a recording session
is active, and hot paths that want to skip even the timestamp read gate
on the module flag directly::

    rec = spans.recording()
    if rec:
        t0 = time.perf_counter()
    ...work...
    if rec:
        spans.record_span("executor/h2d_feed", t0,
                          time.perf_counter() - t0, cat="transfer")

Spans carry a wall-clock start (mapped from perf_counter through the
session epoch, so they merge cleanly with the profiler's JSONL events,
which stamp ``time.time()``), a duration in seconds, the recording
thread id, a category, an optional ``error`` flag, and free-form args.
``chrome_trace.export_chrome_trace`` turns them into trace-event JSON.

Request attribution (trace-id propagation): a thread that is serving a
specific request (or batch of requests) installs a *trace context* —
``with spans.trace_context(ids):`` — and every span the thread records
while inside it carries ``trace_ids``, so a merged Chrome trace (and the
flight recorder) can attribute queue-wait / h2d / execute / d2h spans to
the exact requests in flight.  Orthogonally, ``spans.capture(buf)``
installs a thread-local side buffer: spans recorded by the thread are
ALSO appended to ``buf`` even when no global session is active — the
flight recorder's per-batch collection mechanism.  ``recording()``
reports True when either sink is live, so hot-path gates stay a single
call.

Span hierarchy (parent ids): every recorded span carries a fresh 16-hex
``id``, and a ``parent`` id when one is known — no longer inferred from
timestamps.  Enclosing-span call sites push their own id onto a
thread-local parent stack while their body runs (``parent_scope()`` /
``push_parent``+``pop_parent``), so nested spans record a real edge; a
span recorded after-the-fact picks up ``current_parent()``.  The stack
also accepts a FOREIGN id — a wire server pushes the remote parent
parsed from the request's W3C ``traceparent`` header, so a
cross-process span tree keeps one connected hierarchy per trace id.
"""
from __future__ import annotations

import collections
import contextlib
import threading
import time
import uuid
from typing import Deque, Dict, List, Optional, Sequence

__all__ = [
    "recording", "start_recording", "stop_recording", "record_span",
    "record_instant", "span", "session_dropped", "dropped_total",
    "trace_context", "current_trace_ids", "capture",
    "set_thread_lane", "thread_lanes",
    "new_span_id", "push_parent", "pop_parent", "current_parent",
    "parent_scope",
]

_enabled = False
_lock = threading.Lock()
_buffer: Deque[Dict[str, object]] = collections.deque()
_max_spans: Optional[int] = None  # ring-buffer capacity; None = unbounded
_dropped = 0        # spans dropped by the ring in the current/last session
_dropped_total = 0  # process-lifetime drop total (registry exposition)
_epoch_pc = 0.0    # perf_counter at session start
_epoch_wall = 0.0  # time.time at session start

# process-lifetime perf_counter->wall anchor for spans recorded OUTSIDE
# a session (flight-recorder captures have no session epoch to map
# through; drift over a process lifetime is irrelevant at trace-viewer
# resolution)
_anchor_pc = time.perf_counter()
_anchor_wall = time.time()

_tls = threading.local()  # .trace_ids (tuple) / .capture (list)

# tid -> human lane name for the Chrome-trace export (replica workers,
# dispatcher, prefetch producers register here so the fleet renders as
# named parallel tracks)
_thread_lanes: Dict[int, str] = {}


def recording() -> bool:
    """True while a span sink is live for the calling thread: a global
    trace session, or a thread-local flight-recorder capture."""
    return _enabled or getattr(_tls, "capture", None) is not None


def start_recording(max_spans: Optional[int] = None) -> None:
    """Begin a session: clears the buffer, re-anchors the epoch.

    ``max_spans`` turns the buffer into a drop-oldest ring, so an
    always-on production session holds the LAST N spans instead of
    growing an unbounded list; drops are counted (``session_dropped`` /
    the ``trace_dropped_spans_total`` registry counter).

    Sessions are process-global and do NOT nest: starting a new one
    supersedes (and discards the buffered spans of) any active session,
    and the superseded ``trace_session`` will export empty.  One trace
    session at a time is the contract."""
    global _enabled, _epoch_pc, _epoch_wall, _max_spans, _dropped
    if max_spans is not None and int(max_spans) < 1:
        raise ValueError("max_spans must be >= 1 (got %r)" % (max_spans,))
    with _lock:
        _buffer.clear()
        _max_spans = int(max_spans) if max_spans is not None else None
        _dropped = 0
        _epoch_pc = time.perf_counter()
        _epoch_wall = time.time()
        _enabled = True


def stop_recording() -> List[Dict[str, object]]:
    """End the session; returns (and drains) the recorded spans.  With a
    ring-buffer session these are the LAST ``max_spans`` recorded —
    ``session_dropped()`` says how many older ones fell off."""
    global _enabled
    with _lock:
        _enabled = False
        out = list(_buffer)
        _buffer.clear()
    return out


def session_dropped() -> int:
    """Spans dropped by the ring buffer in the current (or, after
    ``stop_recording``, the most recent) session."""
    return _dropped


def dropped_total() -> int:
    """Process-lifetime ring-buffer drop total (monotonic; backs the
    ``trace_dropped_spans_total`` registry counter)."""
    return _dropped_total


def record_span(name: str, t0: float, dur: float, cat: str = "host",
                error: bool = False, span_id: Optional[str] = None,
                parent: Optional[str] = None, **args) -> None:
    """Record one completed span.  ``t0`` is the perf_counter value at
    span start, ``dur`` the duration in seconds.  No-op when neither a
    session nor a thread-local capture is active.

    ``span_id`` pins the span's id (an enclosing call site that pushed
    the id onto the parent stack while its body ran passes it here);
    omitted, a fresh id is minted.  ``parent`` pins the parent edge;
    omitted, the thread's current parent-stack top (if any) is used."""
    cap = getattr(_tls, "capture", None)
    if not _enabled and cap is None:
        return
    rec: Dict[str, object] = {
        "name": name,
        "cat": cat,
        "dur": float(dur),
        "tid": threading.get_ident(),
        "id": span_id or new_span_id(),
    }
    if parent is None:
        parent = current_parent()
    if parent:
        rec["parent"] = parent
    if error:
        rec["error"] = True
    ids = getattr(_tls, "trace_ids", None)
    if ids:
        rec["trace_ids"] = list(ids)
    if args:
        rec["args"] = args
    if cap is not None:
        # capture-only spans map through the process anchor (no session
        # epoch may exist); when a session IS live the dict is shared, so
        # the session's epoch-mapped ts below overwrites this one
        rec["ts"] = _anchor_wall + (t0 - _anchor_pc)
        cap.append(rec)
    if not _enabled:
        return
    global _dropped, _dropped_total
    with _lock:
        if _enabled:
            # epoch read under the lock: a concurrent start_recording
            # re-anchors both epochs atomically, so the ts can never mix
            # an old perf_counter anchor with a new wall anchor
            rec["ts"] = _epoch_wall + (t0 - _epoch_pc)  # wall-clock seconds
            if _max_spans is not None and len(_buffer) >= _max_spans:
                _buffer.popleft()  # drop-oldest ring
                _dropped += 1
                _dropped_total += 1
            _buffer.append(rec)


def record_instant(name: str, cat: str = "host", **args) -> None:
    """Record a zero-duration marker event."""
    if not recording():
        return
    record_span(name, time.perf_counter(), 0.0, cat=cat, instant=True, **args)


@contextlib.contextmanager
def span(name: str, cat: str = "host", **args):
    """Context-manager form; spans that exit via exception are flagged
    ``error=True``.  Near-zero-cost when no session is active.

    The span's id is pushed onto the parent stack while the body runs,
    so spans recorded inside nest under it (a real parent edge, not a
    timestamp guess)."""
    if not recording():
        yield
        return
    t0 = time.perf_counter()
    sid = push_parent()
    err = False
    try:
        yield
    except BaseException:
        err = True
        raise
    finally:
        pop_parent()
        record_span(name, t0, time.perf_counter() - t0, cat=cat, error=err,
                    span_id=sid, **args)


# ---------------------------------------------------------------------------
# request attribution: trace context + capture buffers + thread lanes
# ---------------------------------------------------------------------------
@contextlib.contextmanager
def trace_context(trace_ids: Optional[Sequence[str]]):
    """Attribute every span this thread records inside the block to the
    given request trace ids (None/empty = no-op).  Nested contexts
    shadow; the previous context is restored on exit."""
    ids = tuple(i for i in (trace_ids or ()) if i)
    if not ids:
        yield
        return
    prev = getattr(_tls, "trace_ids", None)
    _tls.trace_ids = ids
    try:
        yield
    finally:
        _tls.trace_ids = prev


def current_trace_ids() -> tuple:
    """The calling thread's active trace ids (empty tuple outside any
    ``trace_context``)."""
    return getattr(_tls, "trace_ids", None) or ()


# ---------------------------------------------------------------------------
# span hierarchy: per-thread parent stack
# ---------------------------------------------------------------------------
def new_span_id() -> str:
    """Mint a 16-hex span id (same shape as a trace id, distinct space)."""
    return uuid.uuid4().hex[:16]


def push_parent(span_id: Optional[str] = None) -> str:
    """Push a span id onto the calling thread's parent stack (minting a
    fresh one when omitted) and return it.  Spans the thread records
    while it is on top carry it as ``parent``.  Pushing a FOREIGN id
    (e.g. the remote parent from a wire request's ``traceparent``
    header) grafts this thread's spans under a span recorded elsewhere."""
    sid = span_id or new_span_id()
    stack = getattr(_tls, "parents", None)
    if stack is None:
        stack = _tls.parents = []
    stack.append(sid)
    return sid


def pop_parent() -> None:
    stack = getattr(_tls, "parents", None)
    if stack:
        stack.pop()


def current_parent() -> Optional[str]:
    """The calling thread's innermost open parent span id, or None."""
    stack = getattr(_tls, "parents", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def parent_scope(span_id: Optional[str] = None):
    """Context-manager form of ``push_parent``/``pop_parent``; yields
    the pushed id.  The caller that OWNS the enclosing span records it
    afterwards via ``record_span(..., span_id=<yielded id>)``; a caller
    grafting under a remote/foreign parent just passes that id."""
    sid = push_parent(span_id)
    try:
        yield sid
    finally:
        pop_parent()


@contextlib.contextmanager
def capture(buf: List[Dict[str, object]]):
    """Thread-local span side-sink: spans recorded by this thread inside
    the block are appended to ``buf`` — independent of (and in addition
    to) any global trace session.  The flight recorder wraps each batch
    execution in one of these; nesting shadows (innermost wins)."""
    prev = getattr(_tls, "capture", None)
    _tls.capture = buf
    try:
        yield buf
    finally:
        _tls.capture = prev


def wall_ts(t0: float) -> float:
    """Map a ``time.perf_counter()`` reading to wall-clock seconds via
    the process anchor (the timebase capture-mode spans use)."""
    return _anchor_wall + (t0 - _anchor_pc)


def set_thread_lane(name: str) -> None:
    """Name the calling thread's lane in Chrome-trace exports (replica
    workers, dispatchers, prefetch producers).

    Registrations deliberately outlive the thread: exports usually run
    AFTER the server stopped, and the spans its dead workers recorded
    must still carry their lane names.  The costs are bounded and
    cosmetic — one small dict entry per named thread ever created, and
    a later unnamed thread that reuses a dead thread's OS id inherits
    its label until it registers its own (latest registration wins)."""
    _thread_lanes[threading.get_ident()] = str(name)


def thread_lanes() -> Dict[int, str]:
    """Snapshot of tid -> lane-name registrations."""
    return dict(_thread_lanes)
