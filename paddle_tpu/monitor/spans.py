"""Host-side span recording for run-phase tracing.

Instrumentation sites (Executor.run phases, lowering, RecordEvent) call
``record_span`` unconditionally; it is a no-op unless a recording session
is active, and hot paths that want to skip even the timestamp read gate
on the module flag directly::

    rec = spans.recording()
    if rec:
        t0 = time.perf_counter()
    ...work...
    if rec:
        spans.record_span("executor/h2d_feed", t0,
                          time.perf_counter() - t0, cat="transfer")

Spans carry a wall-clock start (mapped from perf_counter through the
session epoch, so they merge cleanly with the profiler's JSONL events,
which stamp ``time.time()``), a duration in seconds, the recording
thread id, a category, an optional ``error`` flag, and free-form args.
``chrome_trace.export_chrome_trace`` turns them into trace-event JSON.
"""
from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Deque, Dict, List, Optional

__all__ = [
    "recording", "start_recording", "stop_recording", "record_span",
    "record_instant", "span", "session_dropped", "dropped_total",
]

_enabled = False
_lock = threading.Lock()
_buffer: Deque[Dict[str, object]] = collections.deque()
_max_spans: Optional[int] = None  # ring-buffer capacity; None = unbounded
_dropped = 0        # spans dropped by the ring in the current/last session
_dropped_total = 0  # process-lifetime drop total (registry exposition)
_epoch_pc = 0.0    # perf_counter at session start
_epoch_wall = 0.0  # time.time at session start


def recording() -> bool:
    """True while a span-recording session is active."""
    return _enabled


def start_recording(max_spans: Optional[int] = None) -> None:
    """Begin a session: clears the buffer, re-anchors the epoch.

    ``max_spans`` turns the buffer into a drop-oldest ring, so an
    always-on production session holds the LAST N spans instead of
    growing an unbounded list; drops are counted (``session_dropped`` /
    the ``trace_dropped_spans_total`` registry counter).

    Sessions are process-global and do NOT nest: starting a new one
    supersedes (and discards the buffered spans of) any active session,
    and the superseded ``trace_session`` will export empty.  One trace
    session at a time is the contract."""
    global _enabled, _epoch_pc, _epoch_wall, _max_spans, _dropped
    if max_spans is not None and int(max_spans) < 1:
        raise ValueError("max_spans must be >= 1 (got %r)" % (max_spans,))
    with _lock:
        _buffer.clear()
        _max_spans = int(max_spans) if max_spans is not None else None
        _dropped = 0
        _epoch_pc = time.perf_counter()
        _epoch_wall = time.time()
        _enabled = True


def stop_recording() -> List[Dict[str, object]]:
    """End the session; returns (and drains) the recorded spans.  With a
    ring-buffer session these are the LAST ``max_spans`` recorded —
    ``session_dropped()`` says how many older ones fell off."""
    global _enabled
    with _lock:
        _enabled = False
        out = list(_buffer)
        _buffer.clear()
    return out


def session_dropped() -> int:
    """Spans dropped by the ring buffer in the current (or, after
    ``stop_recording``, the most recent) session."""
    return _dropped


def dropped_total() -> int:
    """Process-lifetime ring-buffer drop total (monotonic; backs the
    ``trace_dropped_spans_total`` registry counter)."""
    return _dropped_total


def record_span(name: str, t0: float, dur: float, cat: str = "host",
                error: bool = False, **args) -> None:
    """Record one completed span.  ``t0`` is the perf_counter value at
    span start, ``dur`` the duration in seconds.  No-op when no session
    is active."""
    if not _enabled:
        return
    rec: Dict[str, object] = {
        "name": name,
        "cat": cat,
        "dur": float(dur),
        "tid": threading.get_ident(),
    }
    if error:
        rec["error"] = True
    if args:
        rec["args"] = args
    global _dropped, _dropped_total
    with _lock:
        if _enabled:
            # epoch read under the lock: a concurrent start_recording
            # re-anchors both epochs atomically, so the ts can never mix
            # an old perf_counter anchor with a new wall anchor
            rec["ts"] = _epoch_wall + (t0 - _epoch_pc)  # wall-clock seconds
            if _max_spans is not None and len(_buffer) >= _max_spans:
                _buffer.popleft()  # drop-oldest ring
                _dropped += 1
                _dropped_total += 1
            _buffer.append(rec)


def record_instant(name: str, cat: str = "host", **args) -> None:
    """Record a zero-duration marker event."""
    if not _enabled:
        return
    record_span(name, time.perf_counter(), 0.0, cat=cat, instant=True, **args)


@contextlib.contextmanager
def span(name: str, cat: str = "host", **args):
    """Context-manager form; spans that exit via exception are flagged
    ``error=True``.  Near-zero-cost when no session is active."""
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    err = False
    try:
        yield
    except BaseException:
        err = True
        raise
    finally:
        record_span(name, t0, time.perf_counter() - t0, cat=cat, error=err, **args)
