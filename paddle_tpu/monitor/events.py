"""Bounded, severity-tagged operational event ring — the ``/eventz``
surface.

Metrics say *how much*; traces say *where the time went*; this ring says
*what happened*: brownout level transitions, backend retirements and
readmissions, prefix-cache fallbacks, SLO alerts firing and clearing.
Every notable-but-rare state change lands here as one small dict, in a
drop-oldest ring bounded at construction, so the last N operational
events of a process are always one ``/eventz`` GET away — on a child
server directly, or merged across a fleet by the balancer's federated
``/eventz``.

``emit()`` is the single producer call site.  It does three things:

* appends the event to the installed ring (a process-default ring is
  always present — emitting never requires setup);
* increments ``serving_events_total{severity}`` so dashboards can rate
  and alert on event volume without parsing the ring;
* forwards to ``spans.record_instant`` so the span-stream instants that
  previously lived at these call sites stay intact — an active trace
  session still sees the same markers, now with a ``severity`` arg.

Events are deliberately cheap and rare (state *transitions*, not
per-request traffic) — ``emit`` must never appear on a request hot path
(``tools/check_hot_path.py`` enforces this statically).
"""
from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Deque, Dict, List, Optional

from paddle_tpu.monitor import spans as _spans
from paddle_tpu.monitor.registry import REGISTRY

__all__ = [
    "SEVERITIES", "EventRing", "emit", "eventz", "install", "get",
    "uninstall",
]

# ordered least -> most severe; emit() rejects anything else
SEVERITIES = ("info", "warning", "error", "critical")

_EVENTS_TOTAL = REGISTRY.counter(
    "serving_events_total",
    "operational events appended to the /eventz ring, by severity",
    ("severity",))

_DEFAULT_CAPACITY = 512


class EventRing:
    """Drop-oldest ring of operational events.

    Each record: ``{"seq", "ts", "kind", "severity", "message", ...attrs}``
    — ``seq`` is a process-unique monotonic id (merge/dedup key for
    federation), ``ts`` wall-clock seconds, ``kind`` a slash-scoped name
    (``serving/brownout``, ``wire/backend_retired``, ``slo/fired``)."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY):
        if int(capacity) < 1:
            raise ValueError("capacity must be >= 1 (got %r)" % (capacity,))
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: Deque[Dict[str, object]] = collections.deque(
            maxlen=self.capacity)
        self._dropped = 0
        self._seq = itertools.count()

    # ------------------------------------------------------------------
    def emit(self, kind: str, severity: str = "info",
             message: str = "", **attrs) -> Dict[str, object]:
        """Append one event; returns the stored record."""
        if severity not in SEVERITIES:
            raise ValueError("unknown severity %r (want one of %s)"
                             % (severity, ", ".join(SEVERITIES)))
        rec: Dict[str, object] = {
            "ts": time.time(),
            "kind": str(kind),
            "severity": severity,
        }
        if message:
            rec["message"] = str(message)
        for k, v in attrs.items():
            if v is not None:
                rec[k] = v
        with self._lock:
            rec["seq"] = next(self._seq)
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(rec)
        _EVENTS_TOTAL.labels(severity=severity).inc()
        return rec

    # ------------------------------------------------------------------
    def snapshot(self, limit: Optional[int] = None,
                 min_severity: Optional[str] = None
                 ) -> List[Dict[str, object]]:
        """Events oldest -> newest; ``limit`` keeps the newest N,
        ``min_severity`` filters below the given level."""
        with self._lock:
            out = list(self._ring)
        if min_severity is not None:
            floor = SEVERITIES.index(min_severity)
            out = [e for e in out
                   if SEVERITIES.index(e["severity"]) >= floor]
        if limit is not None and limit >= 0:
            out = out[-limit:]
        return out

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def eventz(self, limit: Optional[int] = None) -> Dict[str, object]:
        """The ``/eventz`` document."""
        events = self.snapshot(limit=limit)
        return {
            "capacity": self.capacity,
            "dropped": self.dropped,
            "retained": len(events),
            "events": events,
        }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._dropped = 0


# ---------------------------------------------------------------------------
# module slot — a default ring is always installed, so call sites emit
# unconditionally (mirrors flight.py's install/get, minus the None state)
# ---------------------------------------------------------------------------
_default_ring = EventRing()
_ring: EventRing = _default_ring
_slot_lock = threading.Lock()


def install(capacity: int = _DEFAULT_CAPACITY) -> EventRing:
    """Replace the process event ring (e.g. to size it); returns the new
    ring.  Events already in the old ring are not carried over."""
    global _ring
    ring = EventRing(capacity)
    with _slot_lock:
        _ring = ring
    return ring


def get() -> EventRing:
    """The process event ring (always present)."""
    return _ring


def uninstall() -> None:
    """Restore the process-default ring."""
    global _ring
    with _slot_lock:
        _ring = _default_ring


def emit(kind: str, severity: str = "info", message: str = "",
         cat: str = "event", **attrs) -> Dict[str, object]:
    """Append one operational event to the process ring, count it under
    ``serving_events_total{severity}``, and mirror it into any active
    span stream as an instant (the pre-ring behavior of these sites)."""
    rec = _ring.emit(kind, severity=severity, message=message, **attrs)
    # keep the span-stream instants intact: a live trace session sees
    # the same marker the ring stored (record_instant no-ops otherwise)
    _spans.record_instant(kind, cat=cat, severity=severity, **attrs)
    return rec


def eventz(limit: Optional[int] = None) -> Dict[str, object]:
    """The process ring's ``/eventz`` document."""
    return _ring.eventz(limit=limit)
