"""Tail-sampled per-request flight recorder.

Dapper-style tail sampling for the serving stack: every request gets a
trace id and its spans are *collected* per batch, but full span trees
are *retained* only for the requests worth keeping — slow (latency over
``slow_ms``), errored, or deadline-missed — in a bounded drop-oldest
ring.  That inverts head sampling's blind spot: the p99 request is
exactly the one whose trace survives.

Lifecycle::

    rec = monitor.flight_recorder(capacity=256, slow_ms=50)
    ...serve traffic...              # slow/errored requests accumulate
    rec.snapshot()                   # JSON-ready records, newest first
    rec.export_chrome_trace("slow_requests.json")
    rec.close()                      # uninstall (idempotent)

The recorder is process-global (one per process, like the metrics
registry): the serving server consults ``flight.get()`` per batch and
pays a single ``is None`` check when no recorder is installed — the
idle hot path stays inside the asserted <1% instrumentation bound.
While a recorder IS installed, each batch execution runs under a
``spans.capture()`` buffer, so executor run-phase spans (h2d /
device_execute / d2h), serving spans (queue wait, dispatch,
materialize), and the client span all land in the retained record with
their ``trace_ids`` attribution.

``/tracez`` (serving admin endpoint) serves ``snapshot()`` over HTTP.
"""
from __future__ import annotations

import collections
import threading
import time
import uuid
from typing import Dict, List, Optional, Sequence

from paddle_tpu.monitor import registry as _registry

__all__ = [
    "FlightRecorder", "new_trace_id", "install", "get", "uninstall",
    "span_tree",
]

# retention accounting: requests seen vs kept vs pushed off the ring —
# the knob-tuning signal (an evicted_total climbing fast means slow_ms
# is too low for the traffic, or capacity too small for the tail).
_MON_CONSIDERED = _registry.REGISTRY.counter(
    "flight_requests_considered_total",
    "requests the flight recorder saw (recorder installed)")
_MON_RETAINED = _registry.REGISTRY.counter(
    "flight_requests_retained_total",
    "requests retained by tail sampling (slow/errored/deadline-missed)")
_MON_EVICTED = _registry.REGISTRY.counter(
    "flight_requests_evicted_total",
    "retained requests pushed off the bounded ring (drop-oldest)")


def new_trace_id() -> str:
    """Mint a 16-hex-char request trace id (Dapper-style)."""
    return uuid.uuid4().hex[:16]


def span_tree(spans: Sequence[Dict]) -> List[Dict]:
    """Build the real hierarchy from a record's span dicts via their
    explicit ``id``/``parent`` edges (no timestamp inference): returns a
    forest of ``{"name", "span_id", "dur_ms", "children": [...]}`` nodes,
    roots first by start time.  A span whose parent is not in the set
    (e.g. the remote parent lives in another process's record half)
    roots its own subtree.  Parent cycles are broken by promoting one
    member per cycle to a root (its back-edge cut), so every span always
    appears exactly once and the forest stays JSON-serializable."""
    nodes, order = {}, []
    for s in spans:
        sid = s.get("id")
        node = {
            "name": s.get("name"),
            "span_id": sid,
            "parent_id": s.get("parent"),
            "dur_ms": round(float(s.get("dur", 0.0)) * 1e3, 3),
            "children": [],
        }
        if s.get("error"):
            node["error"] = True
        order.append(node)
        if sid and sid not in nodes:
            nodes[sid] = node
    roots = []
    parent_of = {}
    for node in order:
        parent = nodes.get(node.pop("parent_id", None))
        if parent is None or parent is node:
            roots.append(node)
        else:
            parent["children"].append(node)
            parent_of[id(node)] = parent

    def _mark(start, seen):
        stack = [start]
        while stack:
            n = stack.pop()
            if id(n) in seen:
                continue
            seen.add(id(n))
            stack.extend(n["children"])

    # parent CYCLES in foreign span dicts (a malformed peer) would leave
    # every cycle member a child of another member — unreachable from
    # any root.  Promote one entry node per unreachable component,
    # CUTTING its back-edge (or the forest would be circular and refuse
    # to serialize), so the result really degrades to roots instead of
    # dropping spans.
    reachable = set()
    for r in roots:
        _mark(r, reachable)
    for node in order:
        if id(node) not in reachable:
            parent_of[id(node)]["children"].remove(node)
            roots.append(node)
            _mark(node, reachable)
    return roots


class FlightRecorder:
    """Bounded ring of retained request records.

    A record is a plain JSON-ready dict::

        {"trace_id": ..., "status": "ok"|"error"|"deadline",
         "latency_ms": ..., "ts": <wall seconds at completion>,
         "spans": [span dicts incl. trace_ids], ...extra}

    ``consider()`` applies the tail-sampling policy; ``add_span()``
    appends late spans (the client-side span closes after the server
    retained the record).  All methods are thread-safe.
    """

    def __init__(self, capacity: int = 256, slow_ms: float = 50.0):
        if int(capacity) < 1:
            raise ValueError("capacity must be >= 1 (got %r)" % (capacity,))
        self.capacity = int(capacity)
        self.slow_ms = float(slow_ms)
        self._lock = threading.Lock()
        self._ring: "collections.OrderedDict[str, Dict]" = \
            collections.OrderedDict()

    # ------------------------------------------------------------------
    def consider(self, trace_id: Optional[str], latency_s: float,
                 status: str = "ok",
                 spans: Optional[Sequence[Dict]] = None,
                 **extra) -> bool:
        """Apply the tail-sampling policy to one completed request;
        returns True when the request's trace was retained.  A request
        already retained (e.g. the server kept it and the client later
        reports a deadline) is MERGED — spans appended, status upgraded
        (ok < deadline < error), latency maxed — never double-counted
        (merges do not touch ``flight_requests_considered_total``, so
        the retained/considered tuning ratio stays per-request)."""
        latency_ms = float(latency_s) * 1e3
        keep = status != "ok" or latency_ms >= self.slow_ms
        with self._lock:
            rec = self._ring.get(trace_id) if trace_id else None
            if rec is not None:
                rec["latency_ms"] = max(rec["latency_ms"], latency_ms)
                rank = {"ok": 0, "deadline": 1, "error": 2}
                if rank.get(status, 0) > rank.get(rec["status"], 0):
                    rec["status"] = status
                if spans:
                    self._merge_spans(rec, spans)
                for k, v in extra.items():
                    rec.setdefault(k, v)
                return True
            _MON_CONSIDERED.inc()
            if not keep:
                return False
            rec = {
                "trace_id": trace_id or new_trace_id(),
                "status": str(status),
                "latency_ms": latency_ms,
                "ts": time.time(),
                "spans": [dict(s) for s in (spans or ())],
            }
            rec.update(extra)
            self._ring[rec["trace_id"]] = rec
            _MON_RETAINED.inc()
            while len(self._ring) > self.capacity:
                self._ring.popitem(last=False)
                _MON_EVICTED.inc()
        return True

    @staticmethod
    def _merge_spans(rec: Dict, spans: Sequence[Dict]) -> None:
        """Append spans, deduplicating by span id: a cross-process merge
        can present the same span twice (e.g. a loopback hop whose
        server half shares this process's recorder — the wire response
        echoes spans the recorder already holds)."""
        have = {s.get("id") for s in rec["spans"] if s.get("id")}
        for s in spans:
            sid = s.get("id")
            if sid and sid in have:
                continue
            if sid:
                have.add(sid)
            rec["spans"].append(dict(s))

    def add_span(self, trace_id: Optional[str], span: Dict) -> bool:
        """Append one span to an already-retained record (no-op — and
        False — when the request wasn't sampled; duplicate span ids are
        merged away)."""
        if not trace_id:
            return False
        with self._lock:
            rec = self._ring.get(trace_id)
            if rec is None:
                return False
            self._merge_spans(rec, (span,))
        return True

    def get_record(self, trace_id: str) -> Optional[Dict]:
        with self._lock:
            rec = self._ring.get(trace_id)
            return dict(rec) if rec is not None else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # ------------------------------------------------------------------
    def snapshot(self, limit: Optional[int] = None) -> List[Dict]:
        """Retained records, newest first (JSON-serializable)."""
        with self._lock:
            recs = [dict(r) for r in reversed(self._ring.values())]
        return recs[:limit] if limit is not None else recs

    def statusz(self) -> Dict[str, object]:
        """The ``/tracez`` document: knobs + retained records, each
        carrying its rendered span hierarchy (``tree`` — built from the
        explicit parent ids, so the nesting is real, not inferred)."""
        requests = self.snapshot()
        for rec in requests:
            rec["tree"] = span_tree(rec.get("spans") or ())
        return {
            "capacity": self.capacity,
            "slow_ms": self.slow_ms,
            "retained": len(self),
            "requests": requests,
        }

    def export_chrome_trace(self, path: str, limit: Optional[int] = None,
                            **kw) -> str:
        """Render the retained requests' span trees as one
        Perfetto-loadable trace (``monitor.export_chrome_trace``
        ``requests=`` mode)."""
        from paddle_tpu.monitor.chrome_trace import export_chrome_trace

        return export_chrome_trace(
            path, requests=self.snapshot(limit=limit), **kw)

    # ------------------------------------------------------------------
    def close(self) -> None:
        """Uninstall this recorder from the process slot (records stay
        readable on the handle)."""
        global _recorder
        with _install_lock:
            if _recorder is self:
                _recorder = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# process-global slot (monitor.flight_recorder installs here; serving
# reads it per batch with one attribute load)
# ---------------------------------------------------------------------------
_install_lock = threading.Lock()
_recorder: Optional[FlightRecorder] = None


def install(capacity: int = 256, slow_ms: float = 50.0) -> FlightRecorder:
    """Install (and return) the process flight recorder, superseding any
    previous one — the ``monitor.flight_recorder()`` entry point."""
    global _recorder
    rec = FlightRecorder(capacity=capacity, slow_ms=slow_ms)
    with _install_lock:
        _recorder = rec
    return rec


def get() -> Optional[FlightRecorder]:
    """The installed recorder, or None (the hot-path gate)."""
    return _recorder


def uninstall() -> None:
    global _recorder
    with _install_lock:
        _recorder = None
