"""paddle_tpu.monitor — framework-wide observability.

The reference framework's profiler stack (profiler.py + RecordEvent +
CUPTI DeviceTracer + timeline.py) is a first-class subsystem; this is
its TPU-native counterpart, shared by train, serving, and distributed
paths:

* **Metrics registry** (``registry.py``) — process-global Counter /
  Gauge / Histogram with labels; ``snapshot()`` for programs,
  ``render_text()`` for Prometheus scrapers (the serving ``/metrics``
  endpoint).  Every subsystem registers at import and increments on the
  hot path (a lock + an add; always on).
* **Run-phase spans** (``spans.py``) — Executor.run emits per-phase
  spans (lower / jit_compile on first dispatch per cache key / h2d feed
  transfer / device execute / d2h fetch), RecordEvent blocks mirror in,
  serving batches ride the profiler JSONL stream.  Recording is
  opt-in; when off, instrumentation is a single flag check.
* **Chrome-trace export** (``chrome_trace.py``) — merges spans, the
  JSONL event stream, flight-recorder request trees, AND a
  time-aligned ``jax.profiler`` device timeline into one ``trace.json``
  loadable in chrome://tracing / Perfetto (the ``timeline.py`` analog,
  device lanes included).
* **Request-scoped tracing** (``flight.py`` + span trace contexts) —
  ``new_trace_id()`` / ``trace_context()`` attribute spans to requests;
  ``flight_recorder(capacity, slow_ms)`` tail-samples full span trees
  for slow/errored/deadline-missed requests into a bounded ring served
  by the serving ``/tracez`` endpoint.
* **OpenMetrics + push** (``registry.py`` / ``push.py``) —
  ``expose(openmetrics=True)`` renders OpenMetrics 1.0 with histogram
  exemplars carrying ``trace_id``; ``push_gateway(url, interval_s)``
  ships the registry to a Prometheus pushgateway for batch jobs.

Quickstart::

    from paddle_tpu import monitor, profiler

    with monitor.trace_session(path="trace.json",
                               jsonl_path="events.jsonl") as sess:
        profiler.start_jsonl_trace("events.jsonl")
        ...train / serve...
        profiler.stop_jsonl_trace()
    # trace.json now loads in Perfetto; sess.spans holds the raw spans

    print(monitor.render_text())        # Prometheus exposition
    monitor.snapshot()                  # nested dict of every metric
"""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

from paddle_tpu.monitor.registry import (
    DEFAULT_BUCKETS,
    CallbackCounter,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    merge_expositions,
    parse_exposition,
    relabel_exposition,
)
from paddle_tpu.monitor import events as events
from paddle_tpu.monitor import flight as _flight
from paddle_tpu.monitor import slo as slo
from paddle_tpu.monitor import spans as _spans
from paddle_tpu.monitor import train as train
from paddle_tpu.monitor.events import EventRing, eventz
from paddle_tpu.monitor.events import emit as emit_event
from paddle_tpu.monitor.flight import FlightRecorder, new_trace_id
from paddle_tpu.monitor.push import PushGateway, push_gateway
from paddle_tpu.monitor.spans import (
    current_parent,
    current_trace_ids,
    new_span_id,
    parent_scope,
    record_instant,
    record_span,
    recording,
    set_thread_lane,
    span,
    start_recording,
    stop_recording,
    trace_context,
)
from paddle_tpu.monitor.chrome_trace import export_chrome_trace

# ring-buffer sessions (trace_session(max_spans=N)) count what they drop
REGISTRY.counter_callback(
    "trace_dropped_spans_total",
    "spans dropped by ring-buffer trace sessions (drop-oldest)",
    fn=_spans.dropped_total)

__all__ = [
    "Counter", "Gauge", "Histogram", "CallbackCounter", "MetricsRegistry",
    "REGISTRY", "DEFAULT_BUCKETS",
    "counter", "gauge", "histogram", "counter_callback",
    "snapshot", "render_text", "render_openmetrics", "expose",
    "counter_value",
    "span", "record_span", "record_instant", "recording",
    "start_recording", "stop_recording",
    "trace_context", "current_trace_ids", "set_thread_lane",
    "new_span_id", "parent_scope", "current_parent",
    "new_trace_id", "flight_recorder", "FlightRecorder",
    "events", "EventRing", "emit_event", "eventz",
    "slo", "train",
    "parse_exposition", "relabel_exposition", "merge_expositions",
    "push_gateway", "PushGateway",
    "export_chrome_trace", "trace_session", "TraceSession",
]


# -- process-default registry conveniences ------------------------------
def counter(name: str, help: str = "", labelnames=()) -> Counter:
    return REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "", labelnames=()) -> Gauge:
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name: str, help: str = "", labelnames=(),
              buckets=DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help, labelnames, buckets)


def counter_callback(name: str, help: str = "", fn=None) -> CallbackCounter:
    return REGISTRY.counter_callback(name, help, fn)


def snapshot() -> Dict[str, object]:
    return REGISTRY.snapshot()


def render_text() -> str:
    return REGISTRY.render_text()


def render_openmetrics() -> str:
    return REGISTRY.render_openmetrics()


def expose(openmetrics: bool = False):
    """(body, content_type) for a scrape endpoint — Prometheus 0.0.4 or
    OpenMetrics 1.0 with histogram exemplars."""
    return REGISTRY.expose(openmetrics=openmetrics)


def flight_recorder(capacity: int = 256, slow_ms: float = 50.0) -> FlightRecorder:
    """Install the process flight recorder (tail-sampled per-request
    span trees; see ``monitor.flight``).  Returns the handle — usable as
    a context manager; ``close()`` uninstalls."""
    return _flight.install(capacity=capacity, slow_ms=slow_ms)


def counter_value(name: str, default: float = 0.0, **labels) -> float:
    """Sum of the named counter/gauge's series matching the given label
    subset (bench/test convenience)."""
    return REGISTRY.value(name, default, **labels)


# -- trace sessions -----------------------------------------------------
class TraceSession:
    """Handle yielded by ``trace_session``; after the block exits,
    ``spans`` holds the recorded spans (the last ``max_spans`` of them
    in ring-buffer mode, with ``dropped`` counting the rest) and
    ``export`` re-renders them."""

    def __init__(self, path: Optional[str], jsonl_path: Optional[str],
                 device_trace_dir: Optional[str] = None):
        self.path = path
        self.jsonl_path = jsonl_path
        self.device_trace_dir = device_trace_dir
        self.spans: List[Dict[str, object]] = []
        self.dropped = 0

    def export(self, path: Optional[str] = None,
               jsonl_path: Optional[str] = None,
               device_trace_dir: Optional[str] = None) -> str:
        target = path or self.path
        if target is None:
            raise ValueError("no trace path given")
        return export_chrome_trace(
            target, spans=self.spans,
            jsonl_path=jsonl_path or self.jsonl_path,
            device_trace_dir=device_trace_dir or self.device_trace_dir)


@contextlib.contextmanager
def trace_session(path: Optional[str] = None,
                  jsonl_path: Optional[str] = None,
                  max_spans: Optional[int] = None,
                  device_trace_dir: Optional[str] = None):
    """Record spans for the duration of the block; when ``path`` is
    given, write the merged Chrome trace (spans + ``jsonl_path`` +
    ``device_trace_dir``) on exit — including exceptional exit, so a
    failed run still leaves its trace behind.

    ``device_trace_dir``: a ``jax.profiler`` log dir (the body runs
    ``profiler.start_profiler(trace_dir=...)`` .. ``stop_profiler()``);
    its exported device timeline is time-aligned and merged into the
    trace — one file holds host spans AND the XLA device lanes.

    ``max_spans=N`` bounds the buffer to a drop-oldest ring of N spans,
    making always-on production tracing safe: the session keeps the N
    most recent spans and ``sess.dropped`` (plus the registry's
    ``trace_dropped_spans_total``) counts what fell off."""
    start_recording(max_spans=max_spans)
    sess = TraceSession(path, jsonl_path, device_trace_dir)
    try:
        yield sess
    except BaseException:
        sess.spans = stop_recording()
        sess.dropped = _spans.session_dropped()
        if path is not None:
            try:
                sess.export()
            except Exception:
                pass  # never mask the body's exception with an export error
        raise
    else:
        sess.spans = stop_recording()
        sess.dropped = _spans.session_dropped()
        if path is not None:
            sess.export()
