"""Process-global metrics registry: Counter / Gauge / Histogram with labels.

The framework-wide analog of the reference's per-subsystem stat counters
(platform/profiler.cc event totals, operators/reader queue stats): every
subsystem registers named metrics here once at import, increments them on
the hot path (a lock + an add — safe to leave on unconditionally), and
any consumer reads the whole process through one of two surfaces:

* ``snapshot()`` — a plain nested dict for tests, bench drivers, and the
  serving ``/statusz`` endpoint;
* ``render_text()`` — Prometheus text exposition (version 0.0.4) for the
  serving ``/metrics`` endpoint or any scraper;
* ``render_openmetrics()`` / ``expose(openmetrics=True)`` — OpenMetrics
  1.0 exposition, including histogram *exemplars*: ``observe(v,
  exemplar={"trace_id": ...})`` pins the offending request's trace id to
  the latency bucket it landed in, so a scraper can jump from a p99
  bucket straight to the flight-recorder trace.

Metrics are registered idempotently: re-registering the same name with
the same type/labels returns the existing metric (so module reloads and
multiple importers compose); a mismatch raises.
"""
from __future__ import annotations

import math
import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "CallbackCounter", "MetricsRegistry",
    "REGISTRY", "DEFAULT_BUCKETS",
    "parse_exposition", "relabel_exposition", "merge_expositions",
    "render_exposition", "aggregate_families",
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# seconds-scale latency ladder (Prometheus client default, extended down
# to 100us for host-side dispatch costs)
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class _CounterChild:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1) -> None:
        if n < 0:
            raise ValueError("counters only go up (inc %r)" % (n,))
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class _GaugeChild:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        return self._value


class _HistogramChild:
    __slots__ = ("_lock", "_buckets", "_counts", "_sum", "_count",
                 "_exemplars")

    def __init__(self, buckets: Tuple[float, ...]):
        self._lock = threading.Lock()
        self._buckets = buckets
        self._counts = [0] * len(buckets)  # per-bucket (non-cumulative)
        self._sum = 0.0
        self._count = 0
        # one exemplar slot per bucket + one for +Inf; latest wins.
        # Allocated lazily: most histograms never see an exemplar and
        # the observe() fast path must not pay for the possibility.
        self._exemplars: Optional[List[Optional[tuple]]] = None

    def observe(self, v: float, exemplar: Optional[Dict[str, str]] = None) -> None:
        v = float(v)
        with self._lock:
            self._sum += v
            self._count += 1
            idx = len(self._buckets)  # +Inf slot
            for i, le in enumerate(self._buckets):
                if v <= le:
                    self._counts[i] += 1
                    idx = i
                    break
            if exemplar:
                if self._exemplars is None:
                    self._exemplars = [None] * (len(self._buckets) + 1)
                self._exemplars[idx] = (dict(exemplar), v, time.time())

    def exemplars(self) -> List[Optional[tuple]]:
        """Per-bucket ``(labels, value, wall_ts)`` exemplars (index
        ``len(buckets)`` is +Inf); None where none was ever attached."""
        with self._lock:
            if self._exemplars is None:
                return [None] * (len(self._buckets) + 1)
            return list(self._exemplars)

    @property
    def value(self) -> Dict[str, object]:
        """Snapshot dict: count, sum, and CUMULATIVE bucket counts keyed
        by upper bound (the exposition convention)."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum, out = 0, {}
        for le, c in zip(self._buckets, counts):
            cum += c
            out[_fmt(le)] = cum
        out["+Inf"] = total
        return {"count": total, "sum": s, "buckets": out}


class _BaseMetric:
    kind = "untyped"
    _child_cls = _CounterChild

    def __init__(self, name: str, help: str = "", labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError("invalid metric name %r" % name)
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln == "le":
                raise ValueError("invalid label name %r" % ln)
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        self._default_child = None  # cached no-label child (hot path)

    def _new_child(self):
        return self._child_cls()

    def labels(self, **labelvalues):
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                "metric %r takes labels %s, got %s"
                % (self.name, self.labelnames, tuple(sorted(labelvalues))))
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child()
        return child

    def _default(self):
        """The no-label child (for unlabeled metrics used directly) —
        cached so hot-path ``metric.inc()`` skips the labels() lookup."""
        child = self._default_child
        if child is None:
            child = self._default_child = self.labels()
        return child

    def series(self) -> List[Tuple[Dict[str, str], object]]:
        with self._lock:
            items = list(self._children.items())
        return [
            (dict(zip(self.labelnames, key)), child) for key, child in items
        ]

    def remove_labels(self, **labelvalues) -> None:
        """Drop one labeled child from the exposition (a holder of the
        child object can keep using it; it just stops being scraped).
        Lets short-lived owners — e.g. a stopped serving instance —
        retire their series instead of growing the registry forever."""
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            self._children.pop(key, None)
            if key == ():
                self._default_child = None

    def signature(self):
        return (type(self), self.labelnames)


class Counter(_BaseMetric):
    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, n: float = 1) -> None:
        self._default().inc(n)

    @property
    def value(self) -> float:
        return self._default().value


class Gauge(_BaseMetric):
    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, v: float) -> None:
        self._default().set(v)

    def inc(self, n: float = 1) -> None:
        self._default().inc(n)

    def dec(self, n: float = 1) -> None:
        self._default().dec(n)

    @property
    def value(self) -> float:
        return self._default().value


class Histogram(_BaseMetric):
    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError("histogram needs at least one bucket")
        if math.isinf(b[-1]):
            b = b[:-1]  # +Inf is implicit
        self.buckets = b

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v: float, exemplar: Optional[Dict[str, str]] = None) -> None:
        self._default().observe(v, exemplar=exemplar)

    def signature(self):
        return (type(self), self.labelnames, self.buckets)


class _CallbackChild:
    """Read-only child whose value is computed at scrape time."""

    __slots__ = ("_fn",)

    def __init__(self, fn):
        self._fn = fn

    @property
    def value(self) -> float:
        return float(self._fn())


class CallbackCounter(_BaseMetric):
    """Collect-on-read counter: the value is pulled from ``fn()`` when a
    consumer snapshots/renders, so the producer's hot path pays NOTHING
    beyond whatever bookkeeping it already does (the executor's
    ``_cache_stats`` dicts are the canonical example).  ``fn`` must be
    monotonically non-decreasing to honor counter semantics."""

    kind = "counter"

    def __init__(self, name: str, help: str = "", fn=None):
        super().__init__(name, help, ())
        if fn is None:
            raise ValueError("CallbackCounter %r needs a fn" % name)
        self._fn = fn

    def series(self):
        return [({}, _CallbackChild(self._fn))]

    @property
    def value(self) -> float:
        return float(self._fn())


def _fmt(v: float) -> str:
    return "%.10g" % v


def _escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(labels: Dict[str, str], extra: Optional[Tuple[str, str]] = None) -> str:
    items = sorted(labels.items())
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    return "{%s}" % ",".join('%s="%s"' % (k, _escape(str(v))) for k, v in items)


class MetricsRegistry:
    """A named collection of metrics (the process default is ``REGISTRY``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _BaseMetric] = {}

    # -- registration (idempotent) -------------------------------------
    def _register(self, cls, name, help, labelnames, **kw):
        probe = cls(name, help, labelnames, **kw)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if existing.signature() != probe.signature():
                    raise ValueError(
                        "metric %r already registered as %s%s"
                        % (name, existing.kind, existing.labelnames))
                return existing
            self._metrics[name] = probe
            return probe

    def counter(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "", labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help, labelnames, buckets=buckets)

    def counter_callback(self, name: str, help: str = "", fn=None) -> CallbackCounter:
        """Register a collect-on-read counter (see CallbackCounter).
        Re-registering rebinds ``fn`` (module-reload friendly)."""
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not CallbackCounter:
                    raise ValueError(
                        "metric %r already registered as %s"
                        % (name, existing.kind))
                existing._fn = fn
                return existing
            m = CallbackCounter(name, help, fn=fn)
            self._metrics[name] = m
            return m

    def get(self, name: str) -> Optional[_BaseMetric]:
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def reset(self) -> None:
        """Drop every metric (tests on PRIVATE registries only: metrics
        already handed out as module-level objects keep counting into
        their detached children, so resetting the process default
        silently forks the bookkeeping)."""
        with self._lock:
            self._metrics.clear()

    # -- read surfaces --------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        """{name: {type, help, series: [{labels, value}, ...]}} — values
        are scalars (counter/gauge) or {count, sum, buckets} dicts."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: Dict[str, object] = {}
        for m in sorted(metrics, key=lambda m: m.name):
            out[m.name] = {
                "type": m.kind,
                "help": m.help,
                "series": [
                    {"labels": labels, "value": child.value}
                    for labels, child in m.series()
                ],
            }
        return out

    def value(self, name: str, default: float = 0.0, **labels) -> float:
        """Sum of a counter/gauge's series whose labels contain ``labels``
        as a subset (convenience for tests / bench assertions)."""
        m = self.get(name)
        if m is None:
            return default
        if isinstance(m, Histogram):
            raise TypeError("value() reads counters/gauges, %r is a histogram" % name)
        total, seen = 0.0, False
        for lbls, child in m.series():
            if all(lbls.get(k) == str(v) for k, v in labels.items()):
                total += child.value
                seen = True
        return total if seen else default

    def render_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in sorted(metrics, key=lambda m: m.name):
            if m.help:
                lines.append("# HELP %s %s" % (m.name, m.help.replace("\n", " ")))
            lines.append("# TYPE %s %s" % (m.name, m.kind))
            for labels, child in sorted(m.series(), key=lambda s: sorted(s[0].items())):
                if isinstance(child, _HistogramChild):
                    v = child.value
                    for le, c in v["buckets"].items():
                        lines.append("%s_bucket%s %d" % (
                            m.name, _label_str(labels, ("le", le)), c))
                    lines.append("%s_sum%s %s" % (m.name, _label_str(labels), _fmt(v["sum"])))
                    lines.append("%s_count%s %d" % (m.name, _label_str(labels), v["count"]))
                else:
                    lines.append("%s%s %s" % (m.name, _label_str(labels), _fmt(child.value)))
        return "\n".join(lines) + "\n"

    def render_openmetrics(self) -> str:
        """OpenMetrics 1.0 text exposition.

        Differences from the 0.0.4 format that matter here: counter
        *family* names drop the ``_total`` suffix in HELP/TYPE lines
        (samples keep it), the document ends with ``# EOF``, and
        histogram bucket lines may carry an exemplar —
        ``# {trace_id="..."} <value> <wall_ts>`` — linking the bucket to
        the request that landed in it (the bridge from a p99 latency
        bucket to the flight recorder / merged trace)."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in sorted(metrics, key=lambda m: m.name):
            family = m.name
            if m.kind == "counter" and family.endswith("_total"):
                family = family[: -len("_total")]
            if m.help:
                lines.append("# HELP %s %s" % (family, m.help.replace("\n", " ")))
            lines.append("# TYPE %s %s" % (family, m.kind))
            for labels, child in sorted(m.series(), key=lambda s: sorted(s[0].items())):
                if isinstance(child, _HistogramChild):
                    v = child.value
                    exemplars = child.exemplars()
                    for i, (le, c) in enumerate(v["buckets"].items()):
                        line = "%s_bucket%s %d" % (
                            family, _label_str(labels, ("le", le)), c)
                        ex = exemplars[i] if i < len(exemplars) else None
                        if ex is not None:
                            ex_labels, ex_val, ex_ts = ex
                            line += " # %s %s %.3f" % (
                                _label_str(ex_labels), _fmt(ex_val), ex_ts)
                        lines.append(line)
                    lines.append("%s_sum%s %s" % (family, _label_str(labels), _fmt(v["sum"])))
                    lines.append("%s_count%s %d" % (family, _label_str(labels), v["count"]))
                else:
                    sample = family + "_total" if m.kind == "counter" else family
                    lines.append("%s%s %s" % (sample, _label_str(labels), _fmt(child.value)))
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def expose(self, openmetrics: bool = False) -> Tuple[str, str]:
        """Scrape-ready ``(body, content_type)`` pair: Prometheus text
        0.0.4 by default, OpenMetrics 1.0 (with exemplars) on request —
        the serving ``/metrics`` endpoint negotiates via Accept."""
        if openmetrics:
            return (self.render_openmetrics(),
                    "application/openmetrics-text; version=1.0.0; charset=utf-8")
        return (self.render_text(),
                "text/plain; version=0.0.4; charset=utf-8")


REGISTRY = MetricsRegistry()


# ---------------------------------------------------------------------------
# federation helpers: parse / relabel / merge text expositions
#
# A fleet balancer scrapes each child's /metrics (text 0.0.4 — the
# format render_text() above emits), tags every sample with the child's
# backend id, and re-exposes the union alongside its own registry.  The
# helpers below are that pipeline: text -> family dict -> relabel ->
# merge -> text.  A routing tree of balancers federates transitively
# because relabel PREFIXES an existing backend label instead of
# clobbering it ("edge" scraping a child already labeled backend="b1"
# yields backend="edge/b1").
# ---------------------------------------------------------------------------
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)(?:\s+\S+)?\s*$")
_LABEL_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _unescape(s: str) -> str:
    return re.sub(
        r"\\(.)", lambda m: {"n": "\n"}.get(m.group(1), m.group(1)), s)


def parse_exposition(text: str) -> Dict[str, Dict[str, object]]:
    """Parse a Prometheus text-0.0.4 exposition into an insertion-ordered
    family dict::

        {family: {"type": kind, "help": help,
                  "samples": [(sample_name, labels_dict, value), ...]}}

    ``value`` is a float (``+Inf`` parses to ``inf``).  Histogram
    families keep their flattened ``_bucket``/``_sum``/``_count``
    samples verbatim — merging re-emits them untouched, so federated
    output round-trips exactly.  Unrecognized/comment lines are skipped;
    a sample with no preceding TYPE gets an ``untyped`` family."""
    families: Dict[str, Dict[str, object]] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line == "# EOF":
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                fam = families.setdefault(
                    parts[2], {"type": "untyped", "help": "",
                               "samples": []})
                if parts[1] == "TYPE":
                    fam["type"] = parts[3].strip() if len(parts) > 3 else "untyped"
                else:
                    fam["help"] = parts[3] if len(parts) > 3 else ""
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, label_blob, value_str = m.group(1), m.group(2), m.group(3)
        labels: Dict[str, str] = {}
        if label_blob:
            for k, v in _LABEL_PAIR_RE.findall(label_blob):
                labels[k] = _unescape(v)
        try:
            value = float(value_str)
        except ValueError:
            continue
        family = name
        if family not in families:
            for suffix in _HIST_SUFFIXES:
                if name.endswith(suffix) and name[: -len(suffix)] in families:
                    family = name[: -len(suffix)]
                    break
        fam = families.setdefault(
            family, {"type": "untyped", "help": "", "samples": []})
        fam["samples"].append((name, labels, value))
    return families


def relabel_exposition(families: Dict[str, Dict[str, object]],
                       label: str, value: str,
                       ) -> Dict[str, Dict[str, object]]:
    """A new family dict with ``label=value`` stamped onto every sample.
    A sample that already carries ``label`` (this scrape target is
    itself a federating balancer) gets the new value PREFIXED —
    ``value + "/" + old`` — preserving the full routing path."""
    out: Dict[str, Dict[str, object]] = {}
    for fam_name, fam in families.items():
        samples = []
        for name, labels, v in fam["samples"]:
            labels = dict(labels)
            old = labels.get(label)
            labels[label] = ("%s/%s" % (value, old)) if old else str(value)
            samples.append((name, labels, v))
        out[fam_name] = {"type": fam["type"], "help": fam["help"],
                         "samples": samples}
    return out


def merge_expositions(expositions: Sequence[Dict[str, Dict[str, object]]],
                      ) -> Dict[str, Dict[str, object]]:
    """Merge parsed expositions into one family dict: first-seen HELP /
    TYPE wins per family, samples concatenate in input order.  Callers
    are responsible for label-disjointness (relabel_exposition's
    ``backend`` tag) — duplicate series are emitted as-is."""
    merged: Dict[str, Dict[str, object]] = {}
    for families in expositions:
        for fam_name, fam in families.items():
            into = merged.get(fam_name)
            if into is None:
                merged[fam_name] = {"type": fam["type"], "help": fam["help"],
                                    "samples": list(fam["samples"])}
            else:
                if into["type"] == "untyped" and fam["type"] != "untyped":
                    into["type"] = fam["type"]
                if not into["help"]:
                    into["help"] = fam["help"]
                into["samples"].extend(fam["samples"])
    return merged


def render_exposition(families: Dict[str, Dict[str, object]]) -> str:
    """Render a (parsed/relabeled/merged) family dict back to Prometheus
    text 0.0.4 — one HELP/TYPE block per family name."""
    lines: List[str] = []
    for fam_name in sorted(families):
        fam = families[fam_name]
        if fam["help"]:
            lines.append("# HELP %s %s"
                         % (fam_name, str(fam["help"]).replace("\n", " ")))
        lines.append("# TYPE %s %s" % (fam_name, fam["type"]))
        for name, labels, value in fam["samples"]:
            if float(value) == math.inf:
                val = "+Inf"
            elif value == int(value) and abs(value) < 1e15:
                val = "%d" % int(value)
            else:
                val = _fmt(value)
            lines.append("%s%s %s" % (name, _label_str(labels), val))
    return "\n".join(lines) + "\n"


def aggregate_families(families: Dict[str, Dict[str, object]],
                       quantiles: Sequence[float] = (0.5, 0.99),
                       ) -> Dict[str, Dict[str, object]]:
    """True cross-series aggregates of a (merged) exposition — the
    fleet-/statusz view: counters sum, gauges take the worst case
    (max), histograms merge bucket-wise with count/sum/mean and
    bucket-interpolated quantile estimates::

        {"counters": {name: sum}, "gauges": {name: max},
         "histograms": {name: {"count", "sum", "mean",
                               "p50_est", "p99_est"}}}
    """
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    histograms: Dict[str, Dict[str, object]] = {}
    for fam_name, fam in families.items():
        kind = fam["type"]
        if kind == "counter":
            counters[fam_name] = sum(v for _, _, v in fam["samples"])
        elif kind == "gauge":
            vals = [v for _, _, v in fam["samples"]]
            if vals:
                gauges[fam_name] = max(vals)
        elif kind == "histogram":
            count = 0.0
            total = 0.0
            buckets: Dict[float, float] = {}
            for name, labels, v in fam["samples"]:
                if name.endswith("_count"):
                    count += v
                elif name.endswith("_sum"):
                    total += v
                elif name.endswith("_bucket"):
                    le = labels.get("le", "+Inf")
                    f = math.inf if le == "+Inf" else float(le)
                    buckets[f] = buckets.get(f, 0.0) + v
            agg: Dict[str, object] = {
                "count": count, "sum": total,
                "mean": (total / count) if count else None,
            }
            for q in quantiles:
                key = "p%g_est" % (q * 100.0)
                agg[key] = _bucket_quantile(buckets, count, q)
            histograms[fam_name] = agg
    return {"counters": counters, "gauges": gauges,
            "histograms": histograms}


def _bucket_quantile(buckets: Dict[float, float], count: float,
                     q: float) -> Optional[float]:
    """Linear-interpolated quantile estimate from merged cumulative
    buckets (the textbook Prometheus ``histogram_quantile``)."""
    if not buckets or count <= 0:
        return None
    rank = q * count
    prev_le, prev_cum = 0.0, 0.0
    for le in sorted(buckets):
        cum = buckets[le]
        if cum >= rank:
            if le == math.inf:
                return prev_le if prev_cum else None
            width = le - prev_le
            frac = ((rank - prev_cum) / (cum - prev_cum)
                    if cum > prev_cum else 1.0)
            return prev_le + width * frac
        prev_le, prev_cum = le, cum
    return prev_le
