"""SLO objectives + multi-window multi-burn-rate evaluation.

Declare objectives against registry series — availability from counter
pairs, latency/TTFT bounds from histogram buckets — and evaluate them
the way the Google SRE Workbook prescribes: error rates over *paired*
look-back windows (a short window for responsiveness, a long one to
suppress flapping), alerting when the **burn rate** (windowed error
rate / error budget) clears the pair's threshold in BOTH windows:

* fast pair  — 5m and 1h at burn >= 14.4 (2% of a 30-day budget in an
  hour): page-severity, lands as a ``critical`` event;
* slow pair  — 6h and 3d at burn >= 1.0 (budget merely on track to
  exhaust): ticket-severity, lands as a ``warning`` event.

Everything runs on a background daemon thread off counter/histogram
*deltas* (never the request hot path — ``tools/check_hot_path.py``
enforces this statically): each tick snapshots the registry, appends one
cumulative ``(ts, good, total)`` sample per objective to a bounded
history, and derives windowed rates from sample differences, clamping a
window that reaches past process start to the available history.
Verdicts surface three ways: the ``/sloz`` document, the
``slo_burn_rate{slo,window}`` / ``slo_alert_firing{slo,pair}`` gauge
families, and firing/clearing transitions appended to the operational
event ring (``/eventz``).

The SLO signal is observe-only: nothing in serving reads it for control
decisions by default.

Quickstart::

    from paddle_tpu.monitor import slo

    engine = slo.install([
        slo.availability("infer", good="serving_completed_total",
                         bad=("serving_failed_total",
                              "serving_expired_total"),
                         target=0.999, server="lenet"),
        slo.latency("infer_p99", "serving_request_latency_seconds",
                    threshold_s=0.25, target=0.99, server="lenet"),
        slo.latency("ttft", "serving_decode_ttft_seconds",
                    threshold_s=0.5, target=0.95),
    ], interval_s=10.0)
    ...
    engine.sloz()        # the /sloz document
    slo.uninstall()

``window_scale`` shrinks every window (and both thresholds' meaning
follows automatically) so tests and benches can drive a full
fire-and-clear cycle in seconds.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from paddle_tpu.monitor import events as _events
from paddle_tpu.monitor.registry import REGISTRY, MetricsRegistry

__all__ = [
    "WINDOWS", "PAIRS", "Objective", "availability", "latency",
    "SloEngine", "install", "get", "uninstall",
]

# window label -> seconds (scaled by SloEngine(window_scale=...))
WINDOWS: Dict[str, float] = {
    "5m": 300.0, "1h": 3600.0, "6h": 21600.0, "3d": 259200.0,
}

# (pair name, (short window, long window), burn threshold, severity)
PAIRS: Tuple = (
    ("fast", ("5m", "1h"), 14.4, "critical"),
    ("slow", ("6h", "3d"), 1.0, "warning"),
)


def _as_names(names) -> Tuple[str, ...]:
    if isinstance(names, str):
        return (names,)
    return tuple(names)


def _sum_counters(snap: Dict[str, object], names: Sequence[str],
                  labels: Dict[str, str]) -> float:
    """Sum every series of the named counter families whose labels are a
    superset of ``labels`` (absent family = 0 — objectives may be
    declared before the first request registers the series)."""
    total = 0.0
    for name in names:
        fam = snap.get(name)
        if not fam:
            continue
        for s in fam["series"]:
            slabels = s["labels"]
            if all(slabels.get(k) == v for k, v in labels.items()):
                total += float(s["value"])
    return total


def _merged_histogram(snap: Dict[str, object], name: str,
                      labels: Dict[str, str]):
    """(count, sum, {le_float: cumulative}) merged across every matching
    series of the named histogram family."""
    fam = snap.get(name)
    count = 0.0
    total = 0.0
    buckets: Dict[float, float] = {}
    if not fam:
        return count, total, buckets
    for s in fam["series"]:
        if not all(s["labels"].get(k) == v for k, v in labels.items()):
            continue
        v = s["value"]
        if not isinstance(v, dict):
            continue
        count += float(v.get("count", 0))
        total += float(v.get("sum", 0.0))
        for le, cum in v.get("buckets", {}).items():
            f = float("inf") if le == "+Inf" else float(le)
            buckets[f] = buckets.get(f, 0.0) + float(cum)
    return count, total, buckets


class Objective:
    """One declared objective: ``sample(snapshot)`` returns the
    cumulative ``(good, total)`` event counts the engine differences."""

    kind = "custom"

    def __init__(self, name: str, target: float, description: str = "",
                 sample_fn: Optional[Callable] = None):
        if not 0.0 < float(target) < 1.0:
            raise ValueError(
                "target must be in (0, 1) (got %r)" % (target,))
        self.name = str(name)
        self.target = float(target)
        self.description = description or self.name
        self._sample_fn = sample_fn

    @property
    def budget(self) -> float:
        """The error budget: the tolerated bad-event fraction."""
        return 1.0 - self.target

    def sample(self, snap: Dict[str, object]) -> Tuple[float, float]:
        if self._sample_fn is None:
            raise NotImplementedError
        return self._sample_fn(snap)

    def describe(self) -> Dict[str, object]:
        return {"name": self.name, "kind": self.kind,
                "target": self.target, "description": self.description}


class _Availability(Objective):
    kind = "availability"

    def __init__(self, name: str, good, bad, target: float,
                 description: str, labels: Dict[str, str]):
        super().__init__(name, target, description)
        self.good_metrics = _as_names(good)
        self.bad_metrics = _as_names(bad)
        self.labels = dict(labels)

    def sample(self, snap):
        g = _sum_counters(snap, self.good_metrics, self.labels)
        b = _sum_counters(snap, self.bad_metrics, self.labels)
        return g, g + b

    def describe(self):
        d = super().describe()
        d["good_metrics"] = list(self.good_metrics)
        d["bad_metrics"] = list(self.bad_metrics)
        if self.labels:
            d["labels"] = dict(self.labels)
        return d


class _Latency(Objective):
    kind = "latency"

    def __init__(self, name: str, histogram: str, threshold_s: float,
                 target: float, description: str, labels: Dict[str, str]):
        super().__init__(name, target, description)
        self.histogram = str(histogram)
        self.threshold_s = float(threshold_s)
        self.labels = dict(labels)

    def sample(self, snap):
        count, _, buckets = _merged_histogram(
            snap, self.histogram, self.labels)
        if not buckets:
            return 0.0, 0.0
        # good = observations <= the smallest bucket bound covering the
        # threshold (align thresholds with bucket boundaries for an
        # exact count; otherwise this rounds the bound UP one bucket)
        bounds = sorted(le for le in buckets if le >= self.threshold_s)
        good = buckets[bounds[0]] if bounds else count
        return float(good), float(count)

    def describe(self):
        d = super().describe()
        d["histogram"] = self.histogram
        d["threshold_s"] = self.threshold_s
        if self.labels:
            d["labels"] = dict(self.labels)
        return d


def availability(name: str, good, bad, target: float = 0.999,
                 description: str = "", **labels) -> Objective:
    """``availability >= target`` over counter families: ``good`` /
    ``bad`` are counter names (or sequences of them), summed over every
    series whose labels are a superset of ``**labels``."""
    return _Availability(name, good, bad, target,
                         description or "%s availability >= %g%%"
                         % (name, target * 100.0), labels)


def latency(name: str, histogram: str, threshold_s: float,
            target: float = 0.99, description: str = "",
            **labels) -> Objective:
    """``quantile(target) <= threshold_s`` over a histogram family —
    i.e. at least ``target`` of observations under the threshold.  A
    p99-latency or TTFT bound is this with target 0.99 / 0.95."""
    return _Latency(name, histogram, threshold_s, target,
                    description or "%s p%g <= %gs"
                    % (name, target * 100.0, threshold_s), labels)


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------
_BURN = REGISTRY.gauge(
    "slo_burn_rate",
    "windowed error rate / error budget per objective and look-back "
    "window (1.0 = budget exhausting exactly on schedule)",
    ("slo", "window"))
_FIRING = REGISTRY.gauge(
    "slo_alert_firing",
    "1 while the objective's multi-window burn-rate alert pair is "
    "firing, else 0", ("slo", "pair"))


class SloEngine:
    """Background evaluator for a set of objectives.

    ``interval_s`` is the sampling/evaluation cadence; ``window_scale``
    multiplies every look-back window (tests/benches use e.g. ``0.01``
    to run a fire-and-clear cycle in seconds); ``clock`` is injectable
    for deterministic tests.  ``start()`` spawns the daemon thread;
    ``evaluate_once()`` runs one synchronous tick (usable without
    ``start()``)."""

    def __init__(self, objectives: Iterable[Objective],
                 interval_s: float = 10.0,
                 window_scale: float = 1.0,
                 registry: MetricsRegistry = REGISTRY,
                 clock: Callable[[], float] = time.monotonic):
        self.objectives: List[Objective] = list(objectives)
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError("duplicate objective names: %r" % (names,))
        self.interval_s = float(interval_s)
        self.window_scale = float(window_scale)
        self._registry = registry
        self._clock = clock
        self._windows = {label: secs * self.window_scale
                         for label, secs in WINDOWS.items()}
        self._lock = threading.Lock()
        # objective name -> list of (ts, good, total), oldest first
        self._history: Dict[str, List[Tuple[float, float, float]]] = {
            o.name: [] for o in self.objectives}
        self._max_keep = max(self._windows.values())
        # objective name -> {pair name -> {"firing", "since"}}
        self._alerts: Dict[str, Dict[str, Dict[str, object]]] = {
            o.name: {pair: {"firing": False, "since": None}
                     for pair, _, _, _ in PAIRS}
            for o in self.objectives}
        self._last: Dict[str, Dict[str, object]] = {}
        self._ticks = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def start(self) -> "SloEngine":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="slo-engine", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        # retire this engine's gauge series from the exposition
        for o in self.objectives:
            for w in self._windows:
                _BURN.remove_labels(slo=o.name, window=w)
            for pair, _, _, _ in PAIRS:
                _FIRING.remove_labels(slo=o.name, pair=pair)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_once()
            except Exception:
                pass  # a bad objective must never kill the evaluator

    # ------------------------------------------------------------------
    def evaluate_once(self) -> Dict[str, object]:
        """One tick: sample every objective, derive windowed burn rates,
        update gauges + alert state, emit transition events.  Returns
        the fresh ``/sloz`` document."""
        snap = self._registry.snapshot()
        now = self._clock()
        with self._lock:
            self._ticks += 1
            for obj in self.objectives:
                try:
                    good, total = obj.sample(snap)
                except Exception:
                    continue  # sampled next tick; stale verdict stands
                hist = self._history[obj.name]
                hist.append((now, float(good), float(total)))
                cutoff = now - self._max_keep - 2.0 * self.interval_s
                while len(hist) > 2 and hist[1][0] <= cutoff:
                    hist.pop(0)
                self._last[obj.name] = self._evaluate_locked(
                    obj, hist, now)
            return self._sloz_locked()

    def _evaluate_locked(self, obj: Objective, hist, now: float):
        windows: Dict[str, Dict[str, float]] = {}
        for label, span_s in self._windows.items():
            burn, rate, dt = self._window_burn(obj, hist, now, span_s)
            windows[label] = {
                "burn": round(burn, 4),
                "error_rate": round(rate, 6),
                "span_s": round(dt, 3),
            }
            _BURN.labels(slo=obj.name, window=label).set(round(burn, 4))
        alerts = []
        for pair, (short_w, long_w), threshold, severity in PAIRS:
            firing = (windows[short_w]["burn"] >= threshold
                      and windows[long_w]["burn"] >= threshold)
            state = self._alerts[obj.name][pair]
            if firing != state["firing"]:
                state["firing"] = firing
                state["since"] = time.time()
                _events.emit(
                    "slo/fired" if firing else "slo/cleared",
                    severity=severity if firing else "info",
                    cat="slo", slo=obj.name, pair=pair,
                    threshold=threshold,
                    burn_short=windows[short_w]["burn"],
                    burn_long=windows[long_w]["burn"])
            _FIRING.labels(slo=obj.name, pair=pair).set(
                1.0 if state["firing"] else 0.0)
            alerts.append({
                "pair": pair, "severity": severity,
                "windows": [short_w, long_w], "threshold": threshold,
                "firing": state["firing"], "since": state["since"],
            })
        good, total = hist[-1][1], hist[-1][2]
        verdict = dict(obj.describe())
        verdict.update({
            "good": good, "total": total,
            "windows": windows, "alerts": alerts,
            "ok": not any(a["firing"] for a in alerts),
        })
        return verdict

    def _window_burn(self, obj: Objective, hist, now: float,
                     span_s: float):
        """(burn, error_rate, actual_span) for one look-back window,
        differencing the newest sample against the oldest sample inside
        the window (clamped to full history when the window reaches
        past the first sample)."""
        ts, good, total = hist[-1]
        base = hist[0]
        for rec in hist:
            if rec[0] >= now - span_s:
                base = rec
                break
        dg, dt_total = good - base[1], total - base[2]
        if dt_total <= 0:
            return 0.0, 0.0, ts - base[0]
        rate = min(1.0, max(0.0, 1.0 - dg / dt_total))
        return rate / obj.budget, rate, ts - base[0]

    # ------------------------------------------------------------------
    def sloz(self) -> Dict[str, object]:
        """The ``/sloz`` document (last evaluated verdicts)."""
        with self._lock:
            return self._sloz_locked()

    def _sloz_locked(self) -> Dict[str, object]:
        verdicts = [dict(self._last[o.name]) for o in self.objectives
                    if o.name in self._last]
        return {
            "interval_s": self.interval_s,
            "window_scale": self.window_scale,
            "ticks": self._ticks,
            "ok": all(v["ok"] for v in verdicts) if verdicts else True,
            "objectives": verdicts,
        }


# ---------------------------------------------------------------------------
# module slot (flight.py pattern): the engine /sloz serves
# ---------------------------------------------------------------------------
_engine: Optional[SloEngine] = None
_slot_lock = threading.Lock()


def install(objectives: Iterable[Objective],
            interval_s: float = 10.0,
            window_scale: float = 1.0,
            start: bool = True, **kw) -> SloEngine:
    """Construct the process SLO engine, start its evaluator thread
    (unless ``start=False``), and make it the one ``/sloz`` serves.
    Replaces (and stops) any previously installed engine."""
    global _engine
    engine = SloEngine(objectives, interval_s=interval_s,
                       window_scale=window_scale, **kw)
    with _slot_lock:
        prev, _engine = _engine, engine
    if prev is not None:
        prev.stop()
    if start:
        engine.start()
    return engine


def get() -> Optional[SloEngine]:
    """The installed process engine, or None."""
    return _engine


def uninstall() -> None:
    """Stop and remove the installed engine (idempotent)."""
    global _engine
    with _slot_lock:
        prev, _engine = _engine, None
    if prev is not None:
        prev.stop()


def sloz() -> Dict[str, object]:
    """The process ``/sloz`` document (works with no engine installed —
    admin endpoints stay total)."""
    eng = _engine
    if eng is None:
        return {"installed": False, "ok": True, "objectives": []}
    doc = eng.sloz()
    doc["installed"] = True
    return doc
