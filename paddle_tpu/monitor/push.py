"""Push-gateway exporter: ship the registry to a Prometheus pushgateway.

Batch jobs (bench runs, offline training) finish before any scraper
would pull ``/metrics``; the standard answer is pushing the exposition
to a gateway that holds it for the scraper.  ``monitor.push_gateway(url,
interval_s=30)`` starts a daemon loop PUT-ing the full registry body to
``<url>/metrics/job/<job>`` until ``stop()`` (which pushes one final
snapshot so the terminal state is never lost).

Transport is stdlib urllib — no new dependency — and failures are
counted (``monitor_push_errors_total``) but never raised into the
caller: metrics export must not take the workload down.
"""
from __future__ import annotations

import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from paddle_tpu.monitor import registry as _registry

__all__ = ["PushGateway", "push_gateway"]

_MON_PUSHES = _registry.REGISTRY.counter(
    "monitor_push_total", "successful push-gateway exports")
_MON_PUSH_ERRORS = _registry.REGISTRY.counter(
    "monitor_push_errors_total", "failed push-gateway exports")


class PushGateway:
    """Periodic exporter handle (see module docstring).  Usable as a
    context manager; ``push_now()`` forces an immediate export."""

    def __init__(self, url: str, interval_s: float = 30.0,
                 job: str = "paddle_tpu",
                 registry: Optional[_registry.MetricsRegistry] = None,
                 openmetrics: bool = False, timeout_s: float = 5.0,
                 method: str = "PUT"):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0 (got %r)" % (interval_s,))
        self.url = self._push_url(url, job)
        self.interval_s = float(interval_s)
        self.openmetrics = bool(openmetrics)
        self.timeout_s = float(timeout_s)
        self.method = method
        self._registry = registry if registry is not None else _registry.REGISTRY
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="ptpu-push-gateway", daemon=True)
        self._thread.start()

    @staticmethod
    def _push_url(url: str, job: str) -> str:
        """Pushgateway grouping-key convention: POST/PUT target is
        ``<base>/metrics/job/<job>``; a caller that already encoded the
        full path (any ``/metrics/job/`` segment) is passed through."""
        if "/metrics/job/" in url:
            return url
        return url.rstrip("/") + "/metrics/job/" + urllib.parse.quote(
            job, safe="")

    # ------------------------------------------------------------------
    def push_now(self) -> bool:
        """One export; returns success.  Never raises — failures count
        into ``monitor_push_errors_total``."""
        body, ctype = self._registry.expose(openmetrics=self.openmetrics)
        req = urllib.request.Request(
            self.url, data=body.encode("utf-8"),
            headers={"Content-Type": ctype}, method=self.method)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
        except Exception:  # noqa: BLE001 — export must not kill the job
            _MON_PUSH_ERRORS.inc()
            return False
        _MON_PUSHES.inc()
        return True

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.push_now()

    # ------------------------------------------------------------------
    def stop(self, push_final: bool = True, timeout: float = 10.0) -> None:
        """Stop the loop; by default pushes one final snapshot so the
        job's terminal counters reach the gateway."""
        self._stop.set()
        self._thread.join(timeout)
        if push_final:
            self.push_now()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def push_gateway(url: str, interval_s: float = 30.0, **kw) -> PushGateway:
    """Start a background push loop (the ``monitor.push_gateway`` entry
    point); returns the handle — ``stop()`` it when the job ends."""
    return PushGateway(url, interval_s=interval_s, **kw)
