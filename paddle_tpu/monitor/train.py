"""paddle_tpu.monitor.train — the training control tower.

Serving grew a full observability stack (registry -> tracing -> fleet
federation + SLO burn rates); this module is the TRAINING counterpart,
built around goodput accounting (where did the wall-clock go?) and
health attribution (is this run OK?):

* **Step-phase ledger** (``StepPhaseLedger``) — ``train_from_dataset``
  attributes every wall-clock second of the epoch to one phase:
  ``data_wait`` (reader/prefetch stall), ``h2d``, ``device_execute``,
  ``ps_wait`` (dense+sparse pull joins), ``checkpoint`` (quiesce+save;
  sync and async-commit tracked separately), ``restore_fallback``
  (resume-time restore), ``other`` (loop bookkeeping remainder).
  Accounting is WINDOW-EXCLUSIVE: an outer window charges only the
  seconds not already claimed by a nested charge, so the phases sum to
  the elapsed wall-clock exactly — ``finish_epoch`` asserts the
  measured total never exceeds wall by more than 1% (an overcount means
  double-charged time, a ledger bug worth failing loudly on).
  Exported as ``train_phase_seconds_total{phase=}`` counters plus
  ``train_examples_per_second`` / ``train_steps_per_second`` gauges and
  a static-FLOPs ``train_mfu_ratio`` estimate
  (``estimate_block_flops`` walks the block's matmul/conv op shapes).

* **Anomaly watchdog** (``TrainWatchdog``) — EWMA + z-score detectors
  for NaN/Inf loss, loss spikes, grad-norm blowups, and step-time
  regressions/stragglers.  Each detection lands a severity-tagged
  ``train/anomaly`` event (kind + step) in the process ``EventRing``;
  kinds listed in ``halt_on`` raise a typed ``TrainAnomalyError`` so a
  controller can stop a poisoned run cleanly.  The clock is injectable
  for deterministic tests.

* **Scrapeable surface** — ``Executor.start_train_admin(port=0)``
  (implemented here as ``start_train_admin(executor, ...)``) serves
  ``/metrics`` (Prometheus/OpenMetrics), ``/trainz`` (ledger snapshot +
  last-N step table + watchdog state + checkpoint/resume history),
  ``/statusz``, ``/tracez``, ``/eventz`` and ``/healthz`` — the same
  shapes the fleet federation scraper consumes, so a trainer registers
  as a child of ``FleetBalancer.add_scrape_target`` and shows up in the
  one pane of glass next to the serving backends.

* **Step log** (``StepLog`` / ``replay_step_log``) — a per-step JSONL
  stream (``train_from_dataset(train_log=...)``) replayable offline:
  ``replay_step_log`` rebuilds the phase totals + step table from the
  file, and ``tools/train_top.py --replay`` renders it.

Everything gates on the proven one-is-None-check pattern: a disarmed
train loop pays a single attribute check per step, and the armed ledger
is plain float arithmetic (no allocation, no locking) — the
``bench_dispatch.py --train-obs`` leg pins the armed tax under 2%.
"""
from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from paddle_tpu.monitor import events as _events
from paddle_tpu.monitor import flight as _flight
from paddle_tpu.monitor import registry as _registry

__all__ = [
    "PHASES",
    "StepPhaseLedger",
    "TrainWatchdog",
    "TrainAnomalyError",
    "StepLog",
    "estimate_block_flops",
    "replay_step_log",
    "start_train_admin",
    "stop_train_admin",
    "trainz_doc",
    "batch_examples",
]

PHASES = (
    "data_wait",
    "h2d",
    "device_execute",
    "ps_wait",
    "checkpoint",
    "restore_fallback",
    "other",
)

_PHASE_TOTAL = _registry.REGISTRY.counter(
    "train_phase_seconds_total",
    "train_from_dataset wall-clock seconds attributed per phase "
    "(data_wait|h2d|device_execute|ps_wait|checkpoint|restore_fallback|"
    "other); phases sum to the epoch's elapsed time",
    ("phase",))
_EXAMPLES_PS = _registry.REGISTRY.gauge(
    "train_examples_per_second",
    "training throughput: examples consumed per second (epoch cumulative)")
_STEPS_PS = _registry.REGISTRY.gauge(
    "train_steps_per_second",
    "training throughput: optimizer steps per second (epoch cumulative)")
_MFU_RATIO = _registry.REGISTRY.gauge(
    "train_mfu_ratio",
    "model FLOPs utilization estimate: static per-step block FLOPs "
    "(matmul/conv shapes) x steps/s over the platform peak")


# ---------------------------------------------------------------------------
# Static-FLOPs MFU estimate
# ---------------------------------------------------------------------------
def _default_peak_flops() -> float:
    """Platform peak for the MFU denominator.  Env override first
    (``PADDLE_TPU_PEAK_FLOPS``), else the bench's convention (v5e bf16
    for TPU, nominal 1 TFLOP/s for the CPU testbed)."""
    env = os.environ.get("PADDLE_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    platform = "cpu"
    try:
        import jax

        platform = jax.default_backend()
    except Exception:
        pass
    return {"tpu": 197e12, "cpu": 1e12}.get(platform, 197e12)


def _dim(d, batch: int) -> int:
    # dynamic (-1/None) dims stand in for the observed batch size
    return int(batch) if d is None or int(d) < 0 else int(d)


def _shape(block, name: str, batch: int) -> Optional[List[int]]:
    v = block._find_var_recursive(name) if name else None
    shape = getattr(v, "shape", None)
    if shape is None:
        return None
    return [_dim(d, batch) for d in shape]


def _matmul_like_flops(block, op, batch: int) -> float:
    """2*M*K*N for ``mul``/``matmul`` from the operands' static shapes."""
    xs = op.input("X")
    ys = op.input("Y")
    x = _shape(block, xs[0] if xs else None, batch)
    y = _shape(block, ys[0] if ys else None, batch)
    if not x or not y:
        return 0.0
    if op.type == "mul" or op.type == "mul_grad":
        kx = int(op.attr("x_num_col_dims", 1))
        ky = int(op.attr("y_num_col_dims", 1))
        m = _prod(x[:kx])
        k = _prod(x[kx:])
        n = _prod(y[ky:])
        return 2.0 * m * k * n
    # matmul: batch dims are everything before the trailing two
    tx = bool(op.attr("transpose_X", False))
    ty = bool(op.attr("transpose_Y", False))
    if len(x) < 2 or len(y) < 2:
        return 0.0
    bdims = _prod(x[:-2]) if len(x) > 2 else 1
    m = x[-1] if tx else x[-2]
    k = x[-2] if tx else x[-1]
    n = y[-2] if ty else y[-1]
    return 2.0 * bdims * m * k * n


def _conv2d_flops(block, op, batch: int) -> float:
    outs = op.output("Output")
    filts = op.input("Filter")
    out = _shape(block, outs[0] if outs else None, batch)
    filt = _shape(block, filts[0] if filts else None, batch)
    if not out or not filt or len(filt) != 4:
        return 0.0
    # per output element: one MAC across (C_in/groups * kh * kw)
    return 2.0 * _prod(out) * filt[1] * filt[2] * filt[3]


def _prod(dims) -> int:
    out = 1
    for d in dims:
        out *= int(d)
    return out


def estimate_block_flops(program, batch: int = 1) -> float:
    """Static per-step FLOPs estimate from the program's matmul-family
    op shapes (``mul``/``matmul``/``conv2d``; dynamic dims resolve to
    ``batch``).  Grad ops count double their forward op — the backward
    of one matmul is two matmuls (dX and dY) — which covers a
    forward+backward+optimizer block without tracing it.  Best-effort:
    ops with unresolvable shapes contribute 0, so the MFU gauge is a
    floor, never an overclaim."""
    total = 0.0
    for block in getattr(program, "blocks", []):
        for op in block.ops:
            base = op.type[:-5] if op.type.endswith("_grad") else op.type
            scale = 2.0 if op.type.endswith("_grad") else 1.0
            if base in ("mul", "matmul"):
                total += scale * _matmul_like_flops(block, op, batch)
            elif base == "conv2d":
                if op.type.endswith("_grad"):
                    # grad op outputs Input@GRAD/Filter@GRAD, not Output;
                    # approximate as 2x the forward conv via its inputs
                    fwd = next(
                        (o for o in block.ops
                         if o.type == "conv2d"
                         and o.input("Filter") == op.input("Filter")),
                        None)
                    if fwd is not None:
                        total += 2.0 * _conv2d_flops(block, fwd, batch)
                else:
                    total += _conv2d_flops(block, op, batch)
    return total


def batch_examples(feed) -> int:
    """Leading-dim example count of a feed dict (throughput gauges)."""
    if not isinstance(feed, dict):
        return 0
    for v in feed.values():
        shape = getattr(v, "shape", None)
        if shape is None:
            try:
                return len(v)
            except TypeError:
                continue
        if len(shape):
            return int(shape[0])
    return 0


# ---------------------------------------------------------------------------
# Step-phase ledger
# ---------------------------------------------------------------------------
class StepPhaseLedger:
    """Wall-clock attribution for one ``train_from_dataset`` epoch.

    The accounting contract is WINDOW-EXCLUSIVE nesting: ``charge``
    adds seconds to a phase directly; ``window_begin``/``window_end``
    measure an elapsed interval and charge only the part NOT already
    claimed by charges made inside it.  ``run()`` opens a
    device_execute window around the whole dispatch, so its internal
    h2d / ps_wait charges subtract out; the data_wait iterator wrapper
    likewise excludes the sparse-prefetch joins that run inside
    ``next()``.  The invariant — no second is ever charged twice — is
    what lets ``finish_epoch`` assert phases-sum ~= wall-clock."""

    def __init__(self, step_table: int = 64,
                 flops_per_step: Optional[float] = None,
                 peak_flops: Optional[float] = None,
                 metrics: bool = True, tolerance: float = 0.01):
        self.seconds: Dict[str, float] = {p: 0.0 for p in PHASES}
        self.checkpoint_sync_s = 0.0
        self.checkpoint_commit_s = 0.0
        self.steps: collections.deque = collections.deque(maxlen=step_table)
        self.n_steps = 0
        self.examples_total = 0
        self.flops_per_step = flops_per_step
        self.peak_flops = (float(peak_flops) if peak_flops
                          else _default_peak_flops())
        self.tolerance = float(tolerance)
        self.wall_s = 0.0
        self.epoch_t0: Optional[float] = None
        self._inner = 0.0  # monotone: every charged second, all phases
        self._finished = False
        self._flushed: Dict[str, float] = {p: 0.0 for p in PHASES}
        self._step_mark: Dict[str, float] = dict(self.seconds)
        self._sps = 0.0
        self._eps = 0.0
        self._mfu = 0.0
        # resolve the labeled counter children ONCE — the per-step flush
        # must not pay a labels() dict hash per phase
        self._counters = (
            {p: _PHASE_TOTAL.labels(phase=p) for p in PHASES}
            if metrics else None)

    # hot-path: begin ledger-charge (armed-ledger per-step accounting:
    # plain float arithmetic only — no allocation, no device sync, no
    # event emission; the --train-obs bench pins the armed tax < 2%)
    def begin_epoch(self) -> None:
        self.epoch_t0 = time.perf_counter()
        self._finished = False

    def charge(self, phase: str, seconds: float) -> None:
        if seconds <= 0.0:
            return
        self.seconds[phase] += seconds
        self._inner += seconds

    def window_begin(self) -> Tuple[float, float]:
        return (time.perf_counter(), self._inner)

    def window_end(self, token: Tuple[float, float], phase: str,
                   detail: Optional[str] = None) -> float:
        t0, inner0 = token
        dt = (time.perf_counter() - t0) - (self._inner - inner0)
        if dt > 0.0:
            self.seconds[phase] += dt
            self._inner += dt
            if detail == "sync":
                self.checkpoint_sync_s += dt
            elif detail == "commit":
                self.checkpoint_commit_s += dt
        return dt
    # hot-path: end ledger-charge

    def timed_iter(self, batches) -> Iterator:
        """Wrap the batch iterator: each ``next()`` charges data_wait,
        minus any nested ps_wait the overlapped-prefetch join claimed
        inside it.  Close propagates to the wrapped iterator so the
        prefetch producer still shuts down on early exit."""
        src = iter(batches)
        try:
            while True:
                tok = self.window_begin()
                try:
                    v = next(src)
                except StopIteration:
                    return
                self.window_end(tok, "data_wait")
                yield v
        finally:
            closer = getattr(src, "close", None)
            if closer is not None:
                closer()

    def step_done(self, step: int, duration_s: float, examples: int = 0,
                  loss: Optional[float] = None) -> Dict[str, Any]:
        """Per-step bookkeeping: flush phase deltas to the registry
        counters, refresh the throughput/MFU gauges, append the step-
        table row.  Returns the row (the step log writes it)."""
        self.n_steps += 1
        self.examples_total += int(examples)
        if self._counters is not None:
            for p, child in self._counters.items():
                d = self.seconds[p] - self._flushed[p]
                if d > 0.0:
                    child.inc(d)
                    self._flushed[p] = self.seconds[p]
        elapsed = (time.perf_counter() - self.epoch_t0
                   if self.epoch_t0 is not None else 0.0)
        if elapsed > 0.0:
            self._sps = self.n_steps / elapsed
            self._eps = self.examples_total / elapsed
            if self.flops_per_step and self.peak_flops:
                self._mfu = self.flops_per_step * self._sps / self.peak_flops
        if self._counters is not None:
            _STEPS_PS.set(self._sps)
            _EXAMPLES_PS.set(self._eps)
            _MFU_RATIO.set(self._mfu)
        row: Dict[str, Any] = {
            "step": int(step),
            "duration_s": round(float(duration_s), 6),
            "examples": int(examples),
            "phases": {
                p: round(self.seconds[p] - self._step_mark[p], 6)
                for p in PHASES
                if self.seconds[p] - self._step_mark[p] > 0.0
            },
        }
        if loss is not None:
            row["loss"] = loss if math.isfinite(loss) else repr(loss)
        self._step_mark = dict(self.seconds)
        self.steps.append(row)
        return row

    def finish_epoch(self, strict: bool = True) -> None:
        """Close the epoch: the unattributed remainder lands in
        ``other`` and the 1% sum contract is asserted (strict=False on
        exceptional exits — the epoch's own error must propagate, and a
        partial ledger is still worth reading)."""
        if self._finished or self.epoch_t0 is None:
            return
        self._finished = True
        elapsed = time.perf_counter() - self.epoch_t0
        measured = sum(self.seconds.values())
        self.seconds["other"] += max(0.0, elapsed - measured)
        self.wall_s = elapsed
        if self._counters is not None:
            for p, child in self._counters.items():
                d = self.seconds[p] - self._flushed[p]
                if d > 0.0:
                    child.inc(d)
                    self._flushed[p] = self.seconds[p]
        if strict and measured > elapsed * (1.0 + self.tolerance) + 1e-6:
            raise AssertionError(
                "phase ledger overcount: phases sum to %.6fs but the epoch "
                "wall-clock is %.6fs (> %.0f%% tolerance) — some interval "
                "was charged twice" % (measured, elapsed,
                                       self.tolerance * 100.0))

    def snapshot(self) -> Dict[str, Any]:
        wall = self.wall_s
        if not wall and self.epoch_t0 is not None:
            wall = time.perf_counter() - self.epoch_t0
        total = sum(self.seconds.values())
        return {
            "phases": {p: round(self.seconds[p], 6) for p in PHASES},
            "fractions": {
                p: round(self.seconds[p] / total, 4) if total else 0.0
                for p in PHASES
            },
            "wall_s": round(wall, 6),
            "n_steps": self.n_steps,
            "examples": self.examples_total,
            "steps_per_second": round(self._sps, 4),
            "examples_per_second": round(self._eps, 4),
            "mfu_ratio": round(self._mfu, 6),
            "flops_per_step": self.flops_per_step,
            "peak_flops": self.peak_flops,
            "checkpoint": {
                "sync_s": round(self.checkpoint_sync_s, 6),
                "commit_s": round(self.checkpoint_commit_s, 6),
            },
            "steps": list(self.steps),
            "finished": self._finished,
        }


# ---------------------------------------------------------------------------
# Anomaly watchdog
# ---------------------------------------------------------------------------
class TrainAnomalyError(RuntimeError):
    """Typed halt raised by ``TrainWatchdog`` for kinds in ``halt_on``;
    carries the anomaly kind, the global step, and the offending
    value so a controller can route on it without parsing text."""

    def __init__(self, kind: str, step: int, value=None):
        super().__init__(
            "training anomaly %r at step %d (value=%r)" % (kind, step, value))
        self.kind = kind
        self.step = step
        self.value = value


class _Ewma:
    """EWMA mean + variance (z-score detector state)."""

    __slots__ = ("alpha", "mean", "var", "n")

    def __init__(self, alpha: float):
        self.alpha = float(alpha)
        self.mean = 0.0
        self.var = 0.0
        self.n = 0

    def z(self, x: float) -> float:
        if self.n < 2:
            return 0.0
        return (x - self.mean) / math.sqrt(self.var + 1e-12)

    def update(self, x: float) -> None:
        if self.n == 0:
            self.mean = x
        else:
            d = x - self.mean
            self.mean += self.alpha * d
            self.var = (1.0 - self.alpha) * (self.var + self.alpha * d * d)
        self.n += 1


class TrainWatchdog:
    """EWMA + z-score anomaly detection over the per-step signals.

    Detections (each emits one severity-tagged ``train/anomaly`` event
    with ``kind`` + ``step`` into the process EventRing):

    * ``nan_loss`` (critical) — the loss went NaN/Inf.  Default member
      of ``halt_on``: ``raise_if_halt`` raises ``TrainAnomalyError``.
    * ``loss_spike`` (error) — loss z-score above ``z_threshold`` after
      ``warmup_steps`` observations.
    * ``grad_norm_blowup`` (error) — grad-norm z-score above threshold
      (NaN/Inf grad norm reports here too, as critical).
    * ``step_time_regression`` (warning) — step time z-score above
      threshold AND 1.5x the EWMA mean (the straggler signal; the
      absolute guard keeps micro-jitter on fast steps quiet).

    ``clock`` is injectable (event timestamps / tests); the detector
    itself is driven purely by the values passed to ``observe_step``.
    """

    def __init__(self, loss_index: int = 0, alpha: float = 0.1,
                 z_threshold: float = 8.0, warmup_steps: int = 8,
                 halt_on: Tuple[str, ...] = ("nan_loss",),
                 clock=time.time, history: int = 64):
        self.loss_index = loss_index
        self.z_threshold = float(z_threshold)
        self.warmup_steps = int(warmup_steps)
        self.halt_on = tuple(halt_on or ())
        self.clock = clock
        self.anomalies: collections.deque = collections.deque(maxlen=history)
        self.halted: Optional[Dict[str, Any]] = None
        self.steps_observed = 0
        self._loss = _Ewma(alpha)
        self._grad = _Ewma(alpha)
        self._dur = _Ewma(alpha)

    def _flag(self, found: List[Dict[str, Any]], kind: str, severity: str,
              step: int, value) -> None:
        safe = (float(value) if isinstance(value, (int, float))
                and math.isfinite(value) else repr(value))
        found.append({"kind": kind, "severity": severity,
                      "step": int(step), "value": safe,
                      "ts": float(self.clock())})

    def observe_step(self, step: int, loss: Optional[float] = None,
                     grad_norm: Optional[float] = None,
                     step_time_s: Optional[float] = None
                     ) -> List[Dict[str, Any]]:
        """Feed one step's signals; returns the anomalies found (also
        appended to ``self.anomalies`` and emitted as events).  Does NOT
        raise — callers log the step first, then ``raise_if_halt``."""
        found: List[Dict[str, Any]] = []
        warmed = self.steps_observed >= self.warmup_steps
        if loss is not None:
            loss = float(loss)
            if not math.isfinite(loss):
                self._flag(found, "nan_loss", "critical", step, loss)
            else:
                if warmed and abs(self._loss.z(loss)) > self.z_threshold:
                    self._flag(found, "loss_spike", "error", step, loss)
                self._loss.update(loss)
        if grad_norm is not None:
            grad_norm = float(grad_norm)
            if not math.isfinite(grad_norm):
                self._flag(found, "grad_norm_blowup", "critical",
                           step, grad_norm)
            else:
                if warmed and self._grad.z(grad_norm) > self.z_threshold:
                    self._flag(found, "grad_norm_blowup", "error",
                               step, grad_norm)
                self._grad.update(grad_norm)
        if step_time_s is not None:
            step_time_s = float(step_time_s)
            if (warmed and self._dur.z(step_time_s) > self.z_threshold
                    and step_time_s > 1.5 * self._dur.mean):
                self._flag(found, "step_time_regression", "warning",
                           step, step_time_s)
            self._dur.update(step_time_s)
        self.steps_observed += 1
        for rec in found:
            self.anomalies.append(rec)
            _events.emit("train/anomaly", severity=rec["severity"],
                         message="%s at step %d (value=%s)"
                         % (rec["kind"], rec["step"], rec["value"]),
                         cat="train", anomaly=rec["kind"],
                         step=rec["step"])
        return found

    def raise_if_halt(self, anomalies: List[Dict[str, Any]]) -> None:
        for rec in anomalies:
            if rec["kind"] in self.halt_on:
                self.halted = rec
                raise TrainAnomalyError(rec["kind"], rec["step"],
                                        rec.get("value"))

    def state(self) -> Dict[str, Any]:
        return {
            "steps_observed": self.steps_observed,
            "z_threshold": self.z_threshold,
            "warmup_steps": self.warmup_steps,
            "halt_on": list(self.halt_on),
            "halted": self.halted,
            "loss": {"mean": self._loss.mean,
                     "std": math.sqrt(self._loss.var)},
            "grad_norm": {"mean": self._grad.mean,
                          "std": math.sqrt(self._grad.var)},
            "step_time_s": {"mean": self._dur.mean,
                            "std": math.sqrt(self._dur.var)},
            "anomalies": list(self.anomalies),
        }


# ---------------------------------------------------------------------------
# Per-step JSONL step log
# ---------------------------------------------------------------------------
class StepLog:
    """Append-only JSONL stream of per-step records; line-flushed so a
    ``tail -f`` (or ``train_top --replay``) sees steps as they land."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a")

    def write(self, record: Dict[str, Any]) -> None:
        self._f.write(json.dumps(record, sort_keys=True, default=str) + "\n")
        self._f.flush()

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:
            pass


def replay_step_log(path: str) -> Dict[str, Any]:
    """Rebuild a /trainz-shaped summary from a step log written by
    ``train_from_dataset(train_log=...)`` — phase totals, step table,
    anomaly list — for offline analysis of a run that's gone."""
    phases = {p: 0.0 for p in PHASES}
    steps: List[Dict[str, Any]] = []
    anomalies: List[Dict[str, Any]] = []
    events: List[Dict[str, Any]] = []
    examples = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("event"):
                events.append(rec)
                continue
            steps.append(rec)
            examples += int(rec.get("examples", 0))
            for p, v in (rec.get("phases") or {}).items():
                if p in phases:
                    phases[p] += float(v)
            anomalies.extend(rec.get("anomalies") or [])
    wall = sum(float(r.get("duration_s", 0.0)) for r in steps)
    return {
        "path": path,
        "phases": {p: round(v, 6) for p, v in phases.items()},
        "n_steps": len(steps),
        "examples": examples,
        "wall_s": round(wall, 6),
        "steps_per_second": round(len(steps) / wall, 4) if wall else 0.0,
        "examples_per_second": round(examples / wall, 4) if wall else 0.0,
        "steps": steps[-64:],
        "anomalies": anomalies,
        "events": events,
    }


# ---------------------------------------------------------------------------
# /trainz + the trainer admin endpoint
# ---------------------------------------------------------------------------
def trainz_doc(executor) -> Dict[str, Any]:
    """The ``/trainz`` document: ledger snapshot, watchdog state, and
    the executor's checkpoint/resume bookkeeping (which checkpoint
    served a resume, how many integrity fallbacks it took)."""
    led = getattr(executor, "last_train_ledger", None)
    wd = getattr(executor, "last_train_watchdog", None)
    return {
        "role": "trainer",
        "ledger": led.snapshot() if led is not None else None,
        "watchdog": wd.state() if wd is not None else None,
        "checkpoint": {
            "last_resume_step": getattr(executor, "last_resume_step", None),
            "last_restore_path": getattr(executor, "last_restore_path", None),
            "last_restore_fallbacks": getattr(
                executor, "last_restore_fallbacks", 0),
            "last_restore_stats": getattr(
                executor, "last_restore_stats", None),
        },
        "trace_id": getattr(executor, "last_train_trace_id", None),
        "train_log": getattr(executor, "last_train_log", None),
    }


_admin_lock = threading.Lock()


def start_train_admin(executor, host: str = "127.0.0.1",
                      port: int = 0) -> Tuple[str, int]:
    """Serve the trainer's scrape surface on ``host:port`` (port 0 =
    ephemeral): ``/metrics`` (Prometheus text; OpenMetrics 1.0 with
    exemplars under ``Accept: application/openmetrics-text``),
    ``/trainz``, ``/statusz``, ``/tracez`` (flight recorder), ``/eventz``
    and ``/healthz`` — the same document shapes the fleet federation
    scraper reads from a serving backend, so
    ``FleetBalancer.add_scrape_target`` federates a trainer unchanged.
    Returns the bound ``(host, port)``; repeat calls reuse the running
    server."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _TrainAdminHandler(BaseHTTPRequestHandler):
        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                om = "application/openmetrics-text" in (
                    self.headers.get("Accept") or "")
                text, ctype = _registry.REGISTRY.expose(openmetrics=om)
                body = text.encode("utf-8")
            elif path == "/trainz":
                body = json.dumps(trainz_doc(executor), sort_keys=True,
                                  default=str).encode("utf-8")
                ctype = "application/json"
            elif path == "/statusz":
                doc = {"role": "trainer",
                       "trainz": trainz_doc(executor),
                       "jit_cache": executor.jit_cache_stats(),
                       "registry": _registry.REGISTRY.snapshot()}
                body = json.dumps(doc, sort_keys=True,
                                  default=str).encode("utf-8")
                ctype = "application/json"
            elif path == "/tracez":
                rec = _flight.get()
                doc = ({"recorder": False, "retained": 0, "requests": []}
                       if rec is None else dict(rec.statusz(), recorder=True))
                body = json.dumps(doc, sort_keys=True,
                                  default=str).encode("utf-8")
                ctype = "application/json"
            elif path == "/eventz":
                body = json.dumps(_events.eventz(), sort_keys=True,
                                  default=str).encode("utf-8")
                ctype = "application/json"
            elif path == "/healthz":
                body = json.dumps({"ok": True, "role": "trainer"},
                                  sort_keys=True).encode("utf-8")
                ctype = "application/json"
            else:
                self.send_error(
                    404, "unknown path (try /metrics, /trainz, /statusz, "
                         "/tracez, /eventz or /healthz)")
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # keep scrapes out of stderr
            pass

    with _admin_lock:
        existing = getattr(executor, "_train_admin", None)
        if existing is not None:  # concurrent/repeat start: reuse
            return existing.server_address
        server = ThreadingHTTPServer((host, port), _TrainAdminHandler)
        executor._train_admin = server
        executor._train_admin_thread = threading.Thread(
            target=server.serve_forever, name="train-admin", daemon=True)
        executor._train_admin_thread.start()
        return server.server_address


def stop_train_admin(executor) -> None:
    with _admin_lock:
        server = getattr(executor, "_train_admin", None)
        executor._train_admin = None
        thread = getattr(executor, "_train_admin_thread", None)
        executor._train_admin_thread = None
    if server is not None:
        server.shutdown()
        server.server_close()
    if thread is not None:
        thread.join(timeout=5.0)
