"""recordio_writer shim (reference: python/paddle/fluid/recordio_writer.py
— convert_reader_to_recordio_file over the C++ RecordIOWriter).  The
native chunked/CRC writer lives in native/ (recordio.cc); records are the
serialized per-sample feature lists the MultiSlot DataFeed parses."""
from __future__ import annotations

import contextlib

import numpy as np

from paddle_tpu import native

__all__ = ["convert_reader_to_recordio_file", "convert_reader_to_recordio_files"]


def _serialize_sample(sample) -> bytes:
    parts = []
    for slot in sample:
        arr = np.asarray(slot)
        flat = " ".join(str(v) for v in arr.reshape(-1).tolist())
        parts.append("%d %s" % (arr.size, flat))
    return (" ".join(parts)).encode()


def convert_reader_to_recordio_file(filename, reader_creator, compressor=None,
                                    max_num_records=1000, feed_order=None,
                                    feeder=None):
    """Write every sample from ``reader_creator()`` into one recordio
    file; returns the record count."""
    writer = native.RecordIOWriter(filename)
    n = 0
    for sample in reader_creator():
        writer.write(_serialize_sample(sample))
        n += 1
    writer.close()
    return n


def convert_reader_to_recordio_files(filename, batch_per_file, reader_creator,
                                     compressor=None, max_num_records=1000,
                                     feed_order=None, feeder=None):
    """Shard the reader across multiple recordio files."""
    counts = []
    writer = None
    idx = 0
    n_in_file = 0
    for sample in reader_creator():
        if writer is None:
            writer = native.RecordIOWriter("%s-%05d" % (filename, idx))
        writer.write(_serialize_sample(sample))
        n_in_file += 1
        if n_in_file >= batch_per_file:
            writer.close()
            counts.append(n_in_file)
            writer, n_in_file, idx = None, 0, idx + 1
    if writer is not None:
        writer.close()
        counts.append(n_in_file)
    return counts
