"""Composite network blocks (reference: python/paddle/fluid/nets.py —
simple_img_conv_pool:28, img_conv_group:136, sequence_conv_pool:249,
glu:307, scaled_dot_product_attention:345).

Pure compositions over the layers API; XLA fuses each block into the
surrounding module.
"""
from __future__ import annotations

from paddle_tpu import layers

__all__ = [
    "simple_img_conv_pool",
    "sequence_conv_pool",
    "glu",
    "scaled_dot_product_attention",
    "img_conv_group",
]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    """reference: nets.py:28."""
    conv_out = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=conv_stride, padding=conv_padding, dilation=conv_dilation,
        groups=conv_groups, param_attr=param_attr, bias_attr=bias_attr,
        act=act,
    )
    return layers.pool2d(
        input=conv_out, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, pool_padding=pool_padding,
        global_pooling=global_pooling,
    )


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """reference: nets.py:136 — the VGG conv block."""
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def to_list(v):
        return v if isinstance(v, (list, tuple)) else [v] * len(conv_num_filter)

    paddings = to_list(conv_padding)
    fsizes = to_list(conv_filter_size)
    pattrs = to_list(param_attr)
    with_bn = to_list(conv_with_batchnorm)
    drops = to_list(conv_batchnorm_drop_rate)
    for i, nf in enumerate(conv_num_filter):
        local_act = conv_act if not with_bn[i] else None
        tmp = layers.conv2d(
            input=tmp, num_filters=nf, filter_size=fsizes[i],
            padding=paddings[i], param_attr=pattrs[i], act=local_act,
        )
        if with_bn[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            if drops[i]:
                tmp = layers.dropout(x=tmp, dropout_prob=drops[i])
    return layers.pool2d(input=tmp, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None,
                       seq_len=None):
    """reference: nets.py:249 — the text-conv block."""
    conv_out = layers.sequence_conv(
        input=input, num_filters=num_filters, filter_size=filter_size,
        param_attr=param_attr, bias_attr=bias_attr, act=act, seq_len=seq_len,
    )
    return layers.sequence_pool(conv_out, pool_type, seq_len=seq_len)


def glu(input, dim=-1):
    """reference: nets.py:307 — gated linear unit: split | a * sigmoid(b)."""
    from paddle_tpu.layers import tensor as ltensor

    a, b = ltensor.split(input, num_or_sections=2, dim=dim)
    return a * layers.sigmoid(b)


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """reference: nets.py:345 — multi-head scaled dot-product attention
    over [B, T, D] tensors."""
    from paddle_tpu.layers import tensor as ltensor

    d_key = int(queries.shape[-1]) // num_heads

    def split_heads(x):
        if num_heads == 1:
            return x
        B, T, D = x.shape
        x = ltensor.reshape(x, shape=[0, int(T), num_heads, int(D) // num_heads])
        return ltensor.transpose(x, [0, 2, 1, 3])

    def merge_heads(x):
        if num_heads == 1:
            return x
        x = ltensor.transpose(x, [0, 2, 1, 3])
        s = x.shape
        return ltensor.reshape(x, shape=[0, int(s[1]), int(s[2]) * int(s[3])])

    q, k, v = split_heads(queries), split_heads(keys), split_heads(values)
    scaled = layers.scale(q, scale=d_key ** -0.5)
    product = layers.matmul(scaled, k, transpose_y=True)
    weights = layers.softmax(product)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate)
    return merge_heads(layers.matmul(weights, v))
