"""Transpiler namespace parity (reference: python/paddle/fluid/transpiler/).

* ``DistributeTranspiler`` — the reference's PS program rewriter
  (distribute_transpiler.py:181).  On TPU dense parameters sync via ICI
  collectives (CompiledProgram / fleet), and sparse tables use the host
  parameter server (paddle_tpu/distributed/ps.py); this class keeps the
  API and, in "nccl2"-equivalent collective mode, delegates to the
  GradAllReduce rewriter.
* ``memory_optimize`` / ``release_memory`` — no-ops: XLA buffer
  assignment subsumes the reference's liveness-based reuse pass
  (memory_optimization_transpiler.py).
"""
from __future__ import annotations

from typing import List, Optional

from paddle_tpu import framework
from paddle_tpu.parallel.collective_transpiler import Collective, GradAllReduce, LocalSGD  # noqa: F401

__all__ = [
    "DistributeTranspiler",
    "DistributeTranspilerConfig",
    "GradAllReduce",
    "LocalSGD",
    "memory_optimize",
    "release_memory",
    "HashName",
    "RoundRobin",
]


class DistributeTranspilerConfig:
    """reference: distribute_transpiler.py:131."""

    slice_var_up = True
    split_method = None
    min_block_size = 8192
    mode = "pserver"  # or "nccl2" (collective)
    print_log = False
    wait_port = True


class DistributeTranspiler:
    """reference: distribute_transpiler.py:181."""

    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._collective: Optional[Collective] = None

    def transpile(
        self,
        trainer_id: int,
        program=None,
        pservers: str = "127.0.0.1:6174",
        trainers: int = 1,
        sync_mode: bool = True,
        startup_program=None,
        current_endpoint: str = "127.0.0.1:6174",
    ):
        program = program or framework.default_main_program()
        startup_program = startup_program or framework.default_startup_program()
        if self.config.mode == "nccl2":
            endpoints = [str(i) for i in range(trainers)]
            self._collective = GradAllReduce()
            self._collective.transpile(
                startup_program, program, trainer_id, endpoints, str(trainer_id),
            )
            return
        # pserver mode: dense PS is legacy on TPU; grads still sync via the
        # collective path, sparse tables go through distributed/ps.py
        self.trainer_id = trainer_id
        self.pserver_endpoints = [e for e in pservers.split(",") if e]
        self.trainer_num = trainers
        self.sync_mode = sync_mode
        self.origin_program = program

    def get_trainer_program(self, wait_port: bool = True):
        return self.origin_program

    def get_pserver_program(self, endpoint: str):
        # the TPU build serves sparse tables from distributed/ps.py; dense
        # pserver programs are not generated (SURVEY.md §2.10 maps dense PS
        # to sharded optimizer state over ICI instead)
        prog = framework.Program()
        return prog

    def get_pserver_programs(self, endpoint: str):
        prog = self.get_pserver_program(endpoint)
        return prog, framework.Program()

    def get_startup_program(self, endpoint: str, pserver_program=None):
        return framework.Program()


def memory_optimize(input_program=None, skip_opt_set=None, print_log=False, level=0, skip_grads=False):
    """No-op: XLA buffer assignment performs cross-op reuse (the
    reference's memory_optimization_transpiler.py liveness pass)."""


def release_memory(input_program=None, skip_opt_set=None):
    """No-op (see memory_optimize)."""


class HashName:
    def __init__(self, pserver_endpoints):
        self.endpoints = pserver_endpoints

    def dispatch(self, varlist):
        return [self.endpoints[hash(v.name) % len(self.endpoints)] for v in varlist]


class RoundRobin:
    def __init__(self, pserver_endpoints):
        self.endpoints = pserver_endpoints
        self._i = 0

    def dispatch(self, varlist):
        out = []
        for v in varlist:
            out.append(self.endpoints[self._i % len(self.endpoints)])
            self._i += 1
        return out
