"""Transpiler namespace parity (reference: python/paddle/fluid/transpiler/).

* ``DistributeTranspiler`` — the reference's PS program rewriter
  (distribute_transpiler.py:181).  On TPU dense parameters sync via ICI
  collectives (CompiledProgram / fleet), and sparse tables use the host
  parameter server (paddle_tpu/distributed/ps.py); this class keeps the
  API and, in "nccl2"-equivalent collective mode, delegates to the
  GradAllReduce rewriter.
* ``memory_optimize`` / ``release_memory`` — no-ops: XLA buffer
  assignment subsumes the reference's liveness-based reuse pass
  (memory_optimization_transpiler.py).
"""
from __future__ import annotations

from typing import List, Optional

from paddle_tpu import framework
from paddle_tpu.parallel.collective_transpiler import Collective, GradAllReduce, LocalSGD  # noqa: F401

__all__ = [
    "DistributeTranspiler",
    "DistributeTranspilerConfig",
    "GradAllReduce",
    "InferenceTranspiler",
    "LocalSGD",
    "memory_optimize",
    "release_memory",
    "HashName",
    "RoundRobin",
]


class DistributeTranspilerConfig:
    """reference: distribute_transpiler.py:131."""

    slice_var_up = True
    split_method = None
    min_block_size = 8192
    mode = "pserver"  # or "nccl2" (collective)
    print_log = False
    wait_port = True


class DistributeTranspiler:
    """reference: distribute_transpiler.py:181."""

    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._collective: Optional[Collective] = None

    def transpile(
        self,
        trainer_id: int,
        program=None,
        pservers: str = "127.0.0.1:6174",
        trainers: int = 1,
        sync_mode: bool = True,
        startup_program=None,
        current_endpoint: str = "127.0.0.1:6174",
    ):
        program = program or framework.default_main_program()
        startup_program = startup_program or framework.default_startup_program()
        if self.config.mode == "nccl2":
            endpoints = [str(i) for i in range(trainers)]
            self._collective = GradAllReduce()
            self._collective.transpile(
                startup_program, program, trainer_id, endpoints, str(trainer_id),
            )
            return
        # pserver mode — the legacy dense PS (reference:
        # distribute_transpiler.py:181): the trainer program loses its
        # optimizer-update ops (it computes grads and send/recvs around
        # the compiled step, executor.py _run_dense_ps), and the pserver
        # program serves the params with server-side optimizer state
        # (distributed/ps.py _DenseParam; listen_and_serv_op.cc:109).
        self.trainer_id = trainer_id
        self.pserver_endpoints = [e for e in pservers.split(",") if e]
        self.trainer_num = trainers
        self.sync_mode = sync_mode
        self.origin_program = program
        self._analyze_optimize_ops()

    # update-op types the dense PS can run server-side; everything the
    # reference's listen_and_serv optimize blocks support on this build
    _SERVER_OPTS = ("sgd", "momentum", "adagrad", "adam")

    def _analyze_optimize_ops(self):
        """Find (Param, Grad, LearningRate, optimizer) per parameter."""
        self._param_updates = {}
        block = self.origin_program.global_block()
        for op in block.ops:
            if "Param" in op.inputs and "Grad" in op.inputs and "ParamOut" in op.outputs:
                if op.type not in self._SERVER_OPTS:
                    raise NotImplementedError(
                        "dense PS mode supports server-side %s; program uses "
                        "%r — use collective (nccl2) mode or GeoSGD instead"
                        % (list(self._SERVER_OPTS), op.type)
                    )
                self._param_updates[op.inputs["Param"][0]] = {
                    "grad": op.inputs["Grad"][0],
                    "lr_var": op.inputs["LearningRate"][0],
                    "optimizer": op.type,
                    "attrs": {k: v for k, v in op.attrs.items()
                              if not k.startswith("__")},
                }
        if not self._param_updates:
            raise ValueError(
                "transpile(mode='pserver') found no optimizer update ops — "
                "call minimize() before transpile (reference: "
                "distribute_transpiler.py:272 _has_distributed_lookup_table)"
            )

    def get_trainer_program(self, wait_port: bool = True):
        """Trainer program: optimizer updates stripped; the executor
        pushes grads / pulls params around each step (the send/recv+
        barrier ops of distribute_transpiler.py:320 as host-side calls)."""
        prog = self.origin_program.clone()
        update_params = set(self._param_updates)
        for blk in prog.blocks:
            blk.ops = [
                op for op in blk.ops
                if not (op.type in self._SERVER_OPTS
                        and op.inputs.get("Param", [None])[0] in update_params)
            ]
        prog._dense_ps_ctx = {
            "endpoints": list(self.pserver_endpoints),
            "trainer_id": int(self.trainer_id),
            "n_trainers": int(self.trainer_num),
            "sync": bool(self.sync_mode),
            "params": dict(self._param_updates),
            "step": 0,
            "initialized": False,
        }
        return prog

    def get_pserver_program(self, endpoint: str):
        """Pserver program: running it (Executor.run) starts the dense
        server loop for the params hashed to ``endpoint`` and BLOCKS
        serving, like the reference's listen_and_serv op."""
        if endpoint not in self.pserver_endpoints:
            raise ValueError("%r not in pserver list %s" % (endpoint, self.pserver_endpoints))
        prog = framework.Program()
        block = self.origin_program.global_block()
        prog._pserver_ctx = {
            "endpoint": endpoint,
            "endpoints": list(self.pserver_endpoints),
            "n_trainers": int(self.trainer_num),
            "sync": bool(self.sync_mode),
            "params": {
                name: {
                    "shape": [int(s) for s in block.var(name).shape],
                    "optimizer": desc["optimizer"],
                    "attrs": desc["attrs"],
                }
                for name, desc in self._param_updates.items()
            },
        }
        return prog

    def get_pserver_programs(self, endpoint: str):
        prog = self.get_pserver_program(endpoint)
        return prog, self.get_startup_program(endpoint, prog)

    def get_startup_program(self, endpoint: str, pserver_program=None):
        # dense params are seeded by trainer 0's initial values (the
        # deterministic broadcast in executor.py _run_dense_ps), so the
        # pserver startup is empty on this build
        return framework.Program()


class InferenceTranspiler:
    """reference: transpiler/inference_transpiler.py:25 — fold batch
    normalization into the preceding convolution for inference.

    For every ``conv2d`` whose output feeds exactly one ``batch_norm``
    (is_test), the BN affine transform is folded into the conv filter
    (per-output-channel scale) and a bias (new or merged into an
    existing channel bias), and the batch_norm op is removed.  On the
    XLA path this is a no-op perf-wise (the compiler fuses), but it
    halves the op count of exported models and lets the native C++
    predictor (native/predictor.cc) serve conv nets without a BN kernel
    in the hot loop.  Clone the program (``for_test=True``) before
    transpiling — weights in the scope are rewritten in place.
    """

    def transpile(self, program, place=None, scope=None) -> int:
        import numpy as np

        from paddle_tpu import unique_name
        from paddle_tpu.scope import global_scope

        scope = scope or global_scope()
        block = program.global_block()
        # reader counts over EVERY block (a While/cond sub-block reading
        # the conv output still needs the raw pre-BN values); only
        # single-consumer chains are fused
        readers: dict = {}
        for blk in program.blocks:
            for op in blk.ops:
                for n in op.input_arg_names:
                    readers[n] = readers.get(n, 0) + 1

        def fold_pair(conv_op, bias_op, bn_op, bn_idx):
            """Fold bn (and the optional existing channel-bias add)
            into the conv filter; returns the replacement op spec."""
            w_name = conv_op.inputs["Filter"][0]
            scale_n, bias_n, mean_n, var_n = (
                bn_op.inputs["Scale"][0], bn_op.inputs["Bias"][0],
                bn_op.inputs["Mean"][0], bn_op.inputs["Variance"][0],
            )
            names = [w_name, scale_n, bias_n, mean_n, var_n]
            if bias_op is not None:
                names.append(bias_op.inputs["Y"][0])
            vals = {n: scope.get(n) for n in names}
            missing = [n for n, v in vals.items() if v is None]
            if missing:
                raise RuntimeError(
                    "InferenceTranspiler: vars %s not initialized in "
                    "scope — run startup / load params first" % missing
                )
            w = np.asarray(vals[w_name])            # OIHW
            eps = float(bn_op.attrs.get("epsilon", 1e-5))
            alpha = np.asarray(vals[scale_n]) / np.sqrt(
                np.asarray(vals[var_n]) + eps
            )                                        # [C_out]
            scope.set(
                w_name, (w * alpha[:, None, None, None]).astype(w.dtype)
            )
            if bias_op is not None:
                # BN(conv + b) = alpha*conv + (alpha*(b - mean) + bnbias):
                # merge into the EXISTING channel bias
                b_name = bias_op.inputs["Y"][0]
                b = np.asarray(vals[b_name]).reshape(-1)
                beta = (
                    alpha * (b - np.asarray(vals[mean_n]))
                    + np.asarray(vals[bias_n])
                )
                scope.set(b_name, beta.astype(np.float32))
                # the add now directly produces the BN output name
                bias_op.outputs["Out"] = [bn_op.outputs["Y"][0]]
                block._remove_op(bn_idx)
                return
            beta = np.asarray(vals[bias_n]) - np.asarray(vals[mean_n]) * alpha
            bn_y = bn_op.outputs["Y"][0]
            fused_bias = unique_name.generate(w_name + ".bn_fold_bias")
            block.create_var(
                name=fused_bias, shape=[int(alpha.shape[0])],
                dtype="float32", persistable=True, stop_gradient=True,
            )
            scope.set(fused_bias, beta.astype(np.float32))
            block._remove_op(bn_idx)
            block._insert_op(
                bn_idx,
                type="elementwise_add",
                inputs={"X": [conv_op.outputs["Output"][0]],
                        "Y": [fused_bias]},
                outputs={"Out": [bn_y]},
                attrs={"axis": 1},
            )

        def is_channel_bias_add(op, src_name):
            return (
                op.type == "elementwise_add"
                and op.inputs.get("X", [None])[0] == src_name
                and op.attrs.get("axis") == 1
                and len(op.inputs.get("Y", [])) == 1
            )

        i = 0
        fused = 0
        while i < len(block.ops) - 1:
            op = block.ops[i]
            if not (op.type == "conv2d"
                    and op.attrs.get("data_format", "NCHW") == "NCHW"):
                i += 1
                continue
            conv_out = op.outputs["Output"][0]
            nxt = block.ops[i + 1]
            bias_op = None
            bn_idx = i + 1
            if (is_channel_bias_add(nxt, conv_out)
                    and readers.get(conv_out, 0) == 1
                    and i + 2 < len(block.ops)):
                bias_op = nxt
                bn_idx = i + 2
            bn_op = block.ops[bn_idx] if bn_idx < len(block.ops) else None
            chain_in = (bias_op.outputs["Out"][0] if bias_op is not None
                        else conv_out)
            if not (
                bn_op is not None
                and bn_op.type == "batch_norm"
                and bn_op.attrs.get("is_test")
                and bn_op.inputs.get("X", [None])[0] == chain_in
                and readers.get(chain_in, 0) == 1
            ):
                i += 1
                continue
            fold_pair(op, bias_op, bn_op, bn_idx)
            fused += 1
            i = bn_idx  # continue after the (now replaced) bn position
        program.version += 1
        return fused


def memory_optimize(input_program=None, skip_opt_set=None, print_log=False, level=0, skip_grads=False):
    """No-op: XLA buffer assignment performs cross-op reuse (the
    reference's memory_optimization_transpiler.py liveness pass)."""


def release_memory(input_program=None, skip_opt_set=None):
    """No-op (see memory_optimize)."""


class HashName:
    def __init__(self, pserver_endpoints):
        self.endpoints = pserver_endpoints

    def dispatch(self, varlist):
        return [self.endpoints[hash(v.name) % len(self.endpoints)] for v in varlist]


class RoundRobin:
    def __init__(self, pserver_endpoints):
        self.endpoints = pserver_endpoints
        self._i = 0

    def dispatch(self, varlist):
        out = []
        for v in varlist:
            out.append(self.endpoints[self._i % len(self.endpoints)])
            self._i += 1
        return out
