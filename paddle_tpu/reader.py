"""Input pipeline: PyReader + composable reader decorators.

Reference: python/paddle/fluid/reader.py:47 (PyReader over a
LoDTensorBlockingQueue + graph ``read`` op, reader/double_buffer prefetch)
and python/paddle/reader/decorator.py (shuffle/batch/buffered/...).

TPU design: instead of in-graph reader ops, PyReader is a host-side
background-thread pipeline that converts batches and issues async
``jax.device_put`` — by the time the training step needs batch N+1 it is
already in HBM (the double_buffer analog; this matters even more on TPU
where the host link is the usual bottleneck).  The executor accepts the
resulting device arrays as feeds untouched (executor.py feed passthrough).
"""
from __future__ import annotations

import itertools
import queue
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu import framework
from paddle_tpu import faults as _faults
from paddle_tpu.core import types as core_types
from paddle_tpu.monitor import registry as _mon_registry

# pipeline health counters (paddle_tpu/monitor): a consumer stall means
# the training loop outran the input pipeline (the batch was NOT ready
# in HBM when asked for — the double-buffer failed its job); a producer
# stall is backpressure (the pipeline outran the consumer, which is the
# healthy direction).  Watch the stall seconds ratio on /statusz.
_MON_CONSUMER_STALLS = _mon_registry.REGISTRY.counter(
    "reader_consumer_stalls_total",
    "consumer blocked on an empty prefetch queue (pipeline starved)")
_MON_CONSUMER_STALL_S = _mon_registry.REGISTRY.counter(
    "reader_consumer_stall_seconds_total",
    "seconds the consumer spent waiting on an empty prefetch queue")
_MON_PRODUCER_STALLS = _mon_registry.REGISTRY.counter(
    "reader_producer_stalls_total",
    "producer blocked on a full prefetch queue (backpressure)")
_MON_PRODUCER_STALL_S = _mon_registry.REGISTRY.counter(
    "reader_producer_stall_seconds_total",
    "seconds the producer spent waiting on a full prefetch queue")

__all__ = [
    "PyReader",
    "DataLoader",
    "shuffle",
    "batch",
    "buffered",
    "device_buffered",
    "map_readers",
    "chain",
    "compose",
    "ComposeNotAligned",
    "firstn",
    "cache",
    "Fake",
    "PipeReader",
]


# ---------------------------------------------------------------------------
# Bounded background prefetch with clean shutdown
# ---------------------------------------------------------------------------
_END = object()  # producer-done sentinel

# how often a blocked producer re-checks the stop flag; bounds both the
# shutdown latency and the cost of a consumer that vanished without close()
_STOP_POLL_S = 0.05


class _Prefetcher:
    """One producer thread filling a bounded queue, one consumer.

    The building block behind ``buffered``/``device_buffered`` and the
    executor's ``train_from_dataset`` prefetch.  Guarantees the producer
    thread TERMINATES in every exit mode: source exhausted (sentinel),
    producer exception (re-raised in the consumer), or consumer gone
    (``close()`` sets the stop flag; a blocked ``put`` polls it).  The
    old inline implementations blocked forever on ``q.put`` when the
    consumer exited mid-epoch — a thread leak per abandoned epoch.

    ``transform`` runs IN the producer thread (this is where
    ``device_buffered`` stages batches onto the device, overlapping h2d
    with the consumer's compute).
    """

    def __init__(self, source, size: int, transform: Optional[Callable] = None,
                 name: str = "ptpu-prefetch"):
        self._source = source
        self._transform = transform
        self._q: queue.Queue = queue.Queue(maxsize=max(1, int(size)))
        self._stop = threading.Event()
        self._exc: Optional[BaseException] = None
        self._finished = False
        self._thread = threading.Thread(
            target=self._fill, name=name, daemon=True)
        self._thread.start()

    # --- producer side ---
    def _put(self, item) -> bool:
        """Enqueue; returns False when the consumer closed us."""
        try:
            self._q.put_nowait(item)
            return True
        except queue.Full:
            pass
        _MON_PRODUCER_STALLS.inc()
        t0 = time.perf_counter()
        try:
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=_STOP_POLL_S)
                    return True
                except queue.Full:
                    continue
            return False
        finally:
            _MON_PRODUCER_STALL_S.inc(time.perf_counter() - t0)

    def _fill(self) -> None:
        try:
            src = self._source() if callable(self._source) else self._source
            for item in src:
                if _faults.active is not None:  # disarmed: one is-None gate
                    # prefetch-thread death: the injected error rides the
                    # existing producer-exception channel — surfaced
                    # TYPED in the consumer, thread terminates cleanly
                    _faults.active.faultpoint("reader.prefetch")
                if self._transform is not None:
                    item = self._transform(item)
                if not self._put(item):
                    return  # closed by the consumer
        except BaseException as e:  # noqa: BLE001 — surfaced to the consumer
            self._exc = e
        finally:
            if not self._stop.is_set():
                self._put(_END)

    # --- consumer side ---
    def __iter__(self):
        return self

    # hot-path: begin prefetch_next (consumer pop — batch must already be in HBM)
    def __next__(self):
        if self._finished:
            raise StopIteration
        try:
            item = self._q.get_nowait()
        except queue.Empty:
            _MON_CONSUMER_STALLS.inc()
            t0 = time.perf_counter()
            item = self._q.get()  # the producer's finally guarantees _END
            _MON_CONSUMER_STALL_S.inc(time.perf_counter() - t0)
        if item is _END:
            self._finished = True
            self._thread.join(timeout=5.0)
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        return item
    # hot-path: end prefetch_next

    def close(self) -> None:
        """Stop the producer and release its thread.  Idempotent; safe
        to call with items still queued (they are dropped)."""
        self._finished = True
        self._stop.set()
        # drain so a producer blocked in put() frees immediately rather
        # than waiting out a poll interval
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# Reader decorators (reference: python/paddle/reader/decorator.py)
# ---------------------------------------------------------------------------
def shuffle(reader, buf_size: int, seed: Optional[int] = None):
    def reader_():
        rng = random.Random(seed)
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        rng.shuffle(buf)
        yield from buf

    return reader_


def batch(reader, batch_size: int, drop_last: bool = False):
    def reader_():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return reader_


def buffered(reader, size: int):
    """Prefetch into a bounded queue on a background thread.  The
    producer terminates when the consumer stops early (see _Prefetcher)."""

    def reader_():
        p = _Prefetcher(reader, size)
        try:
            yield from p
        finally:
            p.close()

    return reader_


def _stack_group(group):
    """Assemble one per_step_feed chunk: stack a group of batches on a
    new leading ``steps`` axis.  Supports dict batches (name -> array),
    sequence batches (positional arrays), and bare arrays."""
    first = group[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(b[k]) for b in group]) for k in first}
    if isinstance(first, (list, tuple)):
        return [np.stack([np.asarray(b[i]) for b in group])
                for i in range(len(first))]
    return np.stack([np.asarray(b) for b in group])


def _tree_device_put(item, device):
    """``jax.device_put`` every array in a dict/sequence/bare batch; a
    None device leaves the batch on host (no jax backend available)."""
    if device is None:
        return item
    import jax

    put = lambda a: jax.device_put(a, device)  # noqa: E731
    if isinstance(item, dict):
        return {k: put(v) for k, v in item.items()}
    if isinstance(item, (list, tuple)):
        return [put(v) for v in item]
    return put(item)


class _MeshSharder:
    """Minimal ``feed_sharding`` provider over a bare ``jax.sharding.Mesh``
    (no CompiledProgram): every feed shards its batch dim over the
    mesh's first axis, with a replicated leading ``steps`` axis for
    per_step_feed chunks."""

    def __init__(self, mesh):
        self.mesh = mesh
        self._memo = {}

    def feed_sharding(self, name, ndim, steps_axis=False):
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = (int(ndim), bool(steps_axis))
        sh = self._memo.get(key)
        if sh is None:
            batch = self.mesh.axis_names[0]
            if steps_axis:
                spec = P(None, batch) if ndim >= 2 else P(None)
            else:
                spec = P(batch) if ndim >= 1 else P()
            sh = self._memo[key] = NamedSharding(self.mesh, spec)
        return sh


def _resolve_sharder(compiled):
    """Accept a CompiledProgram (or anything exposing ``feed_sharding``)
    or a bare jax Mesh."""
    if compiled is None:
        return None
    if hasattr(compiled, "feed_sharding"):
        return compiled
    if hasattr(compiled, "axis_names") and hasattr(compiled, "devices"):
        return _MeshSharder(compiled)
    raise TypeError(
        "device_buffered(compiled=...) wants a CompiledProgram or a "
        "jax.sharding.Mesh; got %r" % type(compiled).__name__)


def _tree_shard_put(item, sharder, steps_axis: bool, feed_names=None):
    """Per-shard ``jax.device_put``: each array lands sliced across the
    mesh (every replica's rows go straight to its own HBM — no
    gather-then-scatter downstream).  Dict batches shard by key;
    sequence batches need ``feed_names`` to map positions to feed vars
    (falling back to batch-dim sharding when unnamed)."""
    import jax

    def put(name, a):
        a = np.asarray(a) if not isinstance(a, jax.Array) else a
        return jax.device_put(
            a, sharder.feed_sharding(name, np.ndim(a), steps_axis=steps_axis))

    if isinstance(item, dict):
        return {k: put(k, v) for k, v in item.items()}
    if isinstance(item, (list, tuple)):
        names = list(feed_names) if feed_names else [None] * len(item)
        if len(names) != len(item):
            raise ValueError(
                "sharded prefetch: %d feed_names for a %d-array batch"
                % (len(names), len(item)))
        return [put(n, v) for n, v in zip(names, item)]
    return put(None, item)


def device_buffered(reader, size: int = 2, device="auto",
                    steps: Optional[int] = None, drop_last: bool = True,
                    compiled=None, feed_names: Optional[Sequence[str]] = None):
    """Device-side prefetch: a bounded background thread that
    ``jax.device_put``s batches ahead of the consumer, so feeds arrive
    as ``jax.Array``s and ``Executor.run``'s h2d phase is a passthrough
    (the reference's reader/double_buffer prefetch op pair,
    operators/reader/buffered_reader.cc).

    ``reader``: a reader callable OR an iterable of batches (dicts,
    sequences, or bare arrays).  ``device="auto"`` (default) resolves
    the process-default jax device (degrading to host staging when no
    backend is available); pass an explicit device to pin, or ``None``
    to skip device staging entirely and prefetch host-side.
    ``steps=N`` assembles per_step_feed chunks: N consecutive batches
    stacked on a new leading axis, matching
    ``Executor.run(steps=N, per_step_feed=True)``; a ragged tail of
    fewer than N batches is dropped unless ``drop_last=False``.

    ``compiled`` (sharding-aware mode): a CompiledProgram — or a bare
    ``jax.sharding.Mesh`` — makes the prefetcher stage each batch
    PER SHARD: every feed is ``device_put`` with its resolved
    NamedSharding so each replica's slice lands in its own HBM ahead of
    dispatch, and ``Executor.run`` on that CompiledProgram passes the
    arrays through untouched (no gather-then-scatter on the hot path).
    Composes with ``steps=N``: the chunk's leading steps axis stays
    replicated while the batch axis shards (steps axis x mesh axis).
    ``feed_names`` maps positional (sequence) batches to feed vars.

    Stalls report into the registry reader counters; the producer
    thread shuts down when the consumer exits early (break/exception).
    """
    sharder = _resolve_sharder(compiled)

    def reader_():
        dev = device
        if sharder is not None:
            dev = None  # sharded staging owns placement
        elif dev == "auto":
            try:
                import jax

                dev = jax.devices()[0]
            except Exception:
                dev = None

        def source():
            it = iter(reader() if callable(reader) else reader)
            if steps is None:
                yield from it
                return
            while True:
                group = list(itertools.islice(it, int(steps)))
                if len(group) < int(steps):
                    if group and not drop_last:
                        yield group
                    return
                yield group

        def stage(item):
            if steps is not None:
                item = _stack_group(item)
            if sharder is not None:
                return _tree_shard_put(
                    item, sharder, steps_axis=steps is not None,
                    feed_names=feed_names)
            return _tree_device_put(item, dev)

        p = _Prefetcher(source, size, transform=stage,
                        name="ptpu-prefetch-device")
        try:
            yield from p
        finally:
            p.close()

    return reader_


def map_readers(func: Callable, *readers):
    def reader_():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return reader_


def chain(*readers):
    def reader_():
        for r in readers:
            yield from r()

    return reader_


class ComposeNotAligned(ValueError):
    """reference: reader/decorator.py:145 — raised by ``compose`` when
    ``check_alignment=True`` and the input readers have unequal length."""


def compose(*readers, check_alignment: bool = True):
    def reader_():
        iters = [r() for r in readers]
        sentinel = object()
        for items in itertools.zip_longest(*iters, fillvalue=sentinel):
            if any(it is sentinel for it in items):
                if check_alignment and not all(it is sentinel for it in items):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned"
                    )
                return
            out = []
            for it in items:
                out.extend(it if isinstance(it, tuple) else (it,))
            yield tuple(out)

    return reader_


class Fake:
    """reference: reader/decorator.py:531 — cache the first sample and
    replay it ``data_num`` times (pipeline speed testing)."""

    def __init__(self):
        self.data = None

    def __call__(self, reader, data_num):
        def fake_reader():
            if self.data is None:
                # explicit guard: a bare next() raising StopIteration
                # inside a generator becomes a confusing PEP-479
                # RuntimeError
                it = iter(reader())
                first = list(itertools.islice(it, 1))
                if not first:
                    raise ValueError(
                        "Fake: the wrapped reader yielded no data"
                    )
                self.data = first[0]
            for _ in range(data_num):
                yield self.data

        return fake_reader


class PipeReader:
    """reference: reader/decorator.py:460 — stream data from a shell
    command's stdout (e.g. ``hadoop fs -cat ...``), optionally gzip
    (multi-member streams supported — concatenated .gz files), yielding
    lines via ``get_line``.  A command that exits nonzero raises instead
    of ending the stream silently (a truncated dataset must not look
    like EOF)."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        import subprocess
        import zlib

        if not isinstance(command, str):
            raise TypeError("command must be a string")
        if file_type == "gzip":
            self._zlib = zlib
            self.dec = zlib.decompressobj(32 + zlib.MAX_WBITS)
        elif file_type != "plain":
            raise TypeError("file_type %s is not allowed" % file_type)
        self.file_type = file_type
        self.bufsize = bufsize
        self.process = subprocess.Popen(
            command.split(" "), bufsize=bufsize, stdout=subprocess.PIPE
        )

    def _decompress(self, buff: bytes) -> bytes:
        # a gzip stream of concatenated members (cat a.gz b.gz): each
        # decompressobj stops at its member's end — chain through
        # unused_data with fresh objects or everything after member 1
        # silently vanishes
        out = self.dec.decompress(buff)
        while self.dec.eof and self.dec.unused_data:
            tail = self.dec.unused_data
            self.dec = self._zlib.decompressobj(32 + self._zlib.MAX_WBITS)
            out += self.dec.decompress(tail)
        return out

    def get_line(self, cut_lines=True, line_break="\n"):
        import codecs

        # incremental decode: a multi-byte UTF-8 char split across a
        # bufsize boundary must not raise mid-stream
        decoder = codecs.getincrementaldecoder("utf-8")()
        remained = ""
        while True:
            buff = self.process.stdout.read(self.bufsize)
            if not buff:
                break
            raw = self._decompress(buff) if self.file_type == "gzip" else buff
            text = decoder.decode(raw)
            if not cut_lines:
                if text:
                    yield text
                continue
            parts = (remained + text).split(line_break)
            remained = parts.pop()
            yield from parts
        tail = decoder.decode(b"", final=True)
        remained += tail
        if remained:
            yield remained
        rc = self.process.wait()
        if rc != 0:
            raise RuntimeError(
                "PipeReader command exited with status %d — the stream "
                "may be truncated" % rc
            )


def firstn(reader, n: int):
    def reader_():
        return itertools.islice(reader(), n)

    return reader_


def cache(reader):
    data: List[Any] = []
    loaded = [False]

    def reader_():
        if not loaded[0]:
            data.extend(reader())
            loaded[0] = True
        return iter(data)

    return reader_


# ---------------------------------------------------------------------------
# PyReader
# ---------------------------------------------------------------------------
class PyReader:
    """Iterable data pipeline bound to feed vars (reference: reader.py:47).

    ``for data in reader():`` yields feed dicts whose values are already
    on-device jax Arrays (async-transferred ahead of use).
    """

    def __init__(
        self,
        feed_list: Optional[Sequence] = None,
        capacity: int = 4,
        use_double_buffer: bool = True,
        iterable: bool = True,
        return_list: bool = False,
    ):
        self._feed_vars = list(feed_list or [])
        self._capacity = max(2, int(capacity))
        self._use_double_buffer = use_double_buffer
        self._iterable = iterable
        self._return_list = return_list
        self._generator: Optional[Callable] = None
        self._places = None

    # --- decoration (reference API) ---
    def decorate_sample_list_generator(self, generator, places=None):
        """generator yields lists of sample tuples (one list = one batch)."""

        def batch_gen():
            for samples in generator():
                arrays = []
                for i, var in enumerate(self._feed_vars):
                    col = [s[i] for s in samples]
                    arrays.append(self._to_array(col, var))
                yield arrays

        self._generator = batch_gen
        self._places = places
        return self

    def decorate_batch_generator(self, generator, places=None):
        """generator yields ready batches: tuples/lists of ndarrays."""

        def batch_gen():
            for batch_arrays in generator():
                if isinstance(batch_arrays, dict):
                    arrays = [batch_arrays[v.name] for v in self._feed_vars]
                else:
                    arrays = list(batch_arrays)
                arrays = [
                    self._cast(np.asarray(a), var)
                    for a, var in zip(arrays, self._feed_vars)
                ]
                yield arrays

        self._generator = batch_gen
        self._places = places
        return self

    decorate_tensor_provider = decorate_batch_generator  # legacy alias

    def _cast(self, arr: np.ndarray, var) -> np.ndarray:
        want = core_types.np_dtype(var.dtype)
        return arr.astype(want) if arr.dtype != want else arr

    def _to_array(self, col, var) -> np.ndarray:
        return self._cast(np.stack([np.asarray(c) for c in col]), var)

    # --- iteration ---
    def __call__(self):
        return self._iter()

    def __iter__(self):
        return self._iter()

    def _iter(self):
        if self._generator is None:
            raise RuntimeError("PyReader is not decorated with a generator")
        names = [v.name for v in self._feed_vars]

        # double buffer = device-side prefetch: batches are device_put
        # on the producer thread, so by the time the training step asks
        # for batch N+1 it is already in HBM (and the producer shuts
        # down cleanly if the consumer abandons the epoch).  A
        # CompiledProgram/Mesh passed as ``places`` upgrades this to the
        # sharded mode: each replica's slice is staged in its own HBM.
        compiled = None
        try:
            compiled = _resolve_sharder(self._places)
        except TypeError:
            pass  # legacy places list — single-device staging
        src = (
            device_buffered(self._generator, self._capacity,
                            compiled=compiled, feed_names=names)()
            if self._use_double_buffer else self._generator()
        )
        for arrays in src:
            if self._return_list:
                yield list(arrays)
            else:
                yield dict(zip(names, arrays))

    # --- legacy non-iterable surface ---
    def start(self):
        self._started_iter = self._iter()

    def reset(self):
        self._started_iter = None

    def next(self):
        return next(self._started_iter)


class DataLoader:
    """Minimal parity shim for fluid.io.DataLoader.from_generator."""

    @staticmethod
    def from_generator(feed_list=None, capacity=4, use_double_buffer=True, iterable=True, return_list=False):
        return PyReader(feed_list, capacity, use_double_buffer, iterable, return_list)

def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """reference: python/paddle/reader/decorator.py xmap_readers — map
    ``mapper`` over reader samples with a worker pool (threads here: the
    mappers are numpy-bound and jax arrays must stay in-process)."""
    import queue as _q
    import threading

    def decorated():
        in_q: "_q.Queue" = _q.Queue(buffer_size)
        out_q: "_q.Queue" = _q.Queue(buffer_size)
        END = object()

        def feed():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample))
            for _ in range(process_num):
                in_q.put(END)

        def work():
            while True:
                item = in_q.get()
                if item is END:
                    out_q.put(END)
                    return
                i, sample = item
                out_q.put((i, mapper(sample)))

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()
        done = 0
        pending = {}
        next_i = 0
        while done < process_num:
            item = out_q.get()
            if item is END:
                done += 1
                continue
            if not order:
                yield item[1]
                continue
            pending[item[0]] = item[1]
            while next_i in pending:
                yield pending.pop(next_i)
                next_i += 1
        if order:
            for i in sorted(pending):
                yield pending[i]

    return decorated


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """reference: decorator.py multiprocess_reader — interleave several
    readers concurrently.  Worker THREADS here instead of processes:
    sample generation is numpy/IO-bound and fork would break the jax
    runtime; the interleaving contract is the same."""
    import queue as _q
    import threading

    def decorated():
        out_q: "_q.Queue" = _q.Queue(queue_size)
        END = object()

        def work(r):
            for sample in r():
                out_q.put(sample)
            out_q.put(END)

        for r in readers:
            threading.Thread(target=work, args=(r,), daemon=True).start()
        done = 0
        while done < len(readers):
            item = out_q.get()
            if item is END:
                done += 1
            else:
                yield item

    return decorated
