"""Trainer / DeviceWorker descriptors (reference: framework/trainer.h:38
TrainerBase/MultiTrainer/DistMultiTrainer/PipelineTrainer,
device_worker.h:103 Hogwild/Downpour/Section workers, trainer_desc.proto,
python/paddle/fluid/trainer_desc.py + trainer_factory.py).

TPU-native mapping: the reference's thread-pool of device workers
interpreting ops is replaced by ONE compiled step (executor.py), so
these descriptors configure HOW ``Executor.train_from_dataset`` drives
that step rather than spawning thread workers:

* ``HogwildWorker``  -> plain compiled step per batch (the lock-free
  shared-scope semantics are subsumed: XLA's dataflow has no races).
* ``DownpourWorker`` -> compiled step + distributed-table prefetch/push
  (executor._prefetch_distributed_tables; async via the Communicator).
* ``SectionWorker``  -> the compiled GPipe pipeline
  (parallel/pipeline_program.py; PipelineOptimizer cut_list).
"""
from __future__ import annotations

from typing import List, Optional

__all__ = [
    "TrainerDesc", "MultiTrainer", "DistMultiTrainer", "PipelineTrainer",
    "DeviceWorker", "Hogwild", "DownpourSGD", "Section",
    "TrainerFactory",
]


class DeviceWorker:
    """Base device-worker descriptor (device_worker.h:103)."""

    worker_kind = "Hogwild"

    def __init__(self):
        self._fleet_desc = None
        self._program = None

    def _set_fleet_desc(self, desc):
        self._fleet_desc = desc

    def _set_program(self, program):
        self._program = program


class Hogwild(DeviceWorker):
    """Lock-free shared-scope SGD worker (hogwild_worker.cc) — on TPU
    the compiled step is race-free by construction."""

    worker_kind = "Hogwild"


class DownpourSGD(DeviceWorker):
    """PS pull/push worker (downpour_worker.cc) — maps to the
    distributed-lookup-table prefetch/push the executor already does for
    programs with ``embedding(is_distributed=True)``."""

    worker_kind = "DownpourSGD"


class Section(DeviceWorker):
    """Pipeline stage worker (section_worker.cc:141) — maps to the
    compiled GPipe schedule (PipelineOptimizer with cut_list)."""

    worker_kind = "Section"

    def __init__(self, num_microbatches: int = 1):
        super().__init__()
        self.num_microbatches = num_microbatches


class TrainerDesc:
    """reference: trainer_desc.proto:21 + python trainer_desc.py."""

    def __init__(self):
        self._worker: DeviceWorker = Hogwild()
        self._fetch_vars: List = []
        self._fetch_info: List[str] = []
        self._print_period = 100
        self.thread_num = 1

    def set_device_worker(self, worker: DeviceWorker):
        self._worker = worker

    def set_fetch_var_and_info(self, fetch_vars, fetch_info, print_period):
        self._fetch_vars = list(fetch_vars or [])
        self._fetch_info = list(fetch_info or [])
        self._print_period = print_period

    def set_thread(self, n: int):
        self.thread_num = n  # informational: one compiled step serves all


class MultiTrainer(TrainerDesc):
    """Single-node multi-thread trainer (trainer.h:63) — one compiled
    step; thread_num is accepted for parity."""


class DistMultiTrainer(TrainerDesc):
    """PS-distributed trainer (trainer.h:81) — pair with DownpourSGD and
    bind_distributed_tables."""


class PipelineTrainer(TrainerDesc):
    """Pipeline trainer (trainer.h:95) — pair with Section and a
    PipelineOptimizer-cut program."""


class TrainerFactory:
    """reference: trainer_factory.cc + python trainer_factory.py."""

    _TRAINERS = {
        "MultiTrainer": MultiTrainer,
        "DistMultiTrainer": DistMultiTrainer,
        "PipelineTrainer": PipelineTrainer,
    }
    _WORKERS = {
        "Hogwild": Hogwild,
        "DownpourSGD": DownpourSGD,
        "Section": Section,
    }

    def create_trainer(self, opt_info: Optional[dict] = None) -> TrainerDesc:
        opt_info = opt_info or {}
        trainer = self._TRAINERS[opt_info.get("trainer", "MultiTrainer")]()
        kind = opt_info.get("device_worker", "Hogwild")
        if kind == "Section":
            worker = Section(num_microbatches=int(opt_info.get("num_microbatches", 1)))
        else:
            worker = self._WORKERS[kind]()
        trainer.set_device_worker(worker)
        return trainer
