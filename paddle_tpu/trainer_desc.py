"""Trainer / DeviceWorker descriptors (reference: framework/trainer.h:38
TrainerBase/MultiTrainer/DistMultiTrainer/PipelineTrainer,
device_worker.h:103 Hogwild/Downpour/Section workers, trainer_desc.proto,
python/paddle/fluid/trainer_desc.py + trainer_factory.py).

TPU-native mapping: the reference's thread-pool of device workers
interpreting ops is replaced by ONE compiled step (executor.py), so
these descriptors configure HOW ``Executor.train_from_dataset`` drives
that step rather than spawning thread workers:

* ``HogwildWorker``  -> plain compiled step per batch (the lock-free
  shared-scope semantics are subsumed: XLA's dataflow has no races).
* ``DownpourWorker`` -> compiled step + distributed-table prefetch/push
  (executor._prefetch_distributed_tables; async via the Communicator).
* ``SectionWorker``  -> the compiled GPipe pipeline
  (parallel/pipeline_program.py; PipelineOptimizer cut_list).
"""
from __future__ import annotations

from typing import List, Optional

__all__ = [
    "TrainerDesc", "MultiTrainer", "DistMultiTrainer", "PipelineTrainer",
    "DeviceWorker", "Hogwild", "DownpourSGD", "Section",
    "TrainerFactory",
]


class DeviceWorker:
    """Base device-worker descriptor (device_worker.h:103)."""

    worker_kind = "Hogwild"

    def __init__(self):
        self._fleet_desc = None
        self._program = None

    def _set_fleet_desc(self, desc):
        self._fleet_desc = desc

    def _set_program(self, program):
        self._program = program

    def _prepare(self, program):
        """Hook run by train_from_dataset before the loop — subclasses
        install their runtime behavior here."""


class Hogwild(DeviceWorker):
    """Lock-free shared-scope SGD worker (hogwild_worker.cc) — on TPU
    the compiled step is race-free by construction; on a dense-PS
    trainer program Hogwild means ASYNC updates, so it flips the
    program's PS round to sync=False (each push applies immediately,
    no cross-trainer barrier — the hogwild contract)."""

    worker_kind = "Hogwild"

    def _prepare(self, program):
        ctx = getattr(program, "_dense_ps_ctx", None)
        if ctx is not None and ctx.get("sync"):
            if ctx.get("initialized"):
                raise ValueError(
                    "Hogwild worker on an already-initialized SYNC dense-PS "
                    "program — transpile with sync_mode=False instead"
                )
            ctx["sync"] = False


class DownpourSGD(DeviceWorker):
    """PS pull/push worker (downpour_worker.cc) — drives the
    distributed-lookup-table prefetch/push through the ASYNC
    Communicator (merge-before-send background thread), installing one
    on the program when none is bound (reference: downpour_worker.cc
    push_sparse via the communicator)."""

    worker_kind = "DownpourSGD"

    def __init__(self, max_merge: int = 20, capacity: int = 200):
        super().__init__()
        self.max_merge = int(max_merge)
        self.capacity = int(capacity)

    def _prepare(self, program):
        client = getattr(program, "_ps_client", None)
        if client is not None and getattr(program, "_ps_communicator", None) is None:
            from paddle_tpu.distributed.communicator import Communicator

            program._ps_communicator = Communicator(
                client, max_merge=self.max_merge, capacity=self.capacity
            ).start()


class Section(DeviceWorker):
    """Pipeline stage worker (section_worker.cc:141) — maps to the
    compiled GPipe schedule (PipelineOptimizer with cut_list)."""

    worker_kind = "Section"

    def __init__(self, num_microbatches: int = 1):
        super().__init__()
        self.num_microbatches = num_microbatches

    def _prepare(self, program):
        plan = getattr(program, "_pipeline_plan", None)
        if plan is not None and self.num_microbatches > 1 and (
            int(plan["num_microbatches"]) != int(self.num_microbatches)
        ):
            raise ValueError(
                "Section worker num_microbatches=%d disagrees with the "
                "program's PipelineOptimizer plan (%d)"
                % (self.num_microbatches, plan["num_microbatches"])
            )


class TrainerDesc:
    """reference: trainer_desc.proto:21 + python trainer_desc.py."""

    def __init__(self):
        self._worker: DeviceWorker = Hogwild()
        self._fetch_vars: List = []
        self._fetch_info: List[str] = []
        self._print_period = 100
        self.thread_num = 1

    def set_device_worker(self, worker: DeviceWorker):
        self._worker = worker

    def set_fetch_var_and_info(self, fetch_vars, fetch_info, print_period):
        self._fetch_vars = list(fetch_vars or [])
        self._fetch_info = list(fetch_info or [])
        self._print_period = print_period

    def set_thread(self, n: int):
        # one compiled step serves all compute threads; n maps to the
        # host-side batch-prefetch depth in train_from_dataset (the
        # reference's reader threads feeding device workers)
        self.thread_num = n


class MultiTrainer(TrainerDesc):
    """Single-node multi-thread trainer (trainer.h:63) — one compiled
    step; thread_num is accepted for parity."""


class DistMultiTrainer(TrainerDesc):
    """PS-distributed trainer (trainer.h:81) — pair with DownpourSGD and
    bind_distributed_tables."""


class PipelineTrainer(TrainerDesc):
    """Pipeline trainer (trainer.h:95) — pair with Section and a
    PipelineOptimizer-cut program."""


class TrainerFactory:
    """reference: trainer_factory.cc + python trainer_factory.py."""

    _TRAINERS = {
        "MultiTrainer": MultiTrainer,
        "DistMultiTrainer": DistMultiTrainer,
        "PipelineTrainer": PipelineTrainer,
    }
    _WORKERS = {
        "Hogwild": Hogwild,
        "DownpourSGD": DownpourSGD,
        "Section": Section,
    }

    def create_trainer(self, opt_info: Optional[dict] = None) -> TrainerDesc:
        opt_info = opt_info or {}
        trainer = self._TRAINERS[opt_info.get("trainer", "MultiTrainer")]()
        kind = opt_info.get("device_worker", "Hogwild")
        if kind == "Section":
            worker = Section(num_microbatches=int(opt_info.get("num_microbatches", 1)))
        else:
            worker = self._WORKERS[kind]()
        trainer.set_device_worker(worker)
        return trainer
