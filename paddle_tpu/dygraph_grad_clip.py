"""Dygraph gradient clipping (reference: python/paddle/fluid/
dygraph_grad_clip.py — GradClipByValue/Norm/GlobalNorm applied to
(param, grad) lists in eager mode)."""
from __future__ import annotations

import numpy as np

__all__ = ["GradClipByValue", "GradClipByNorm", "GradClipByGlobalNorm"]


class GradClipByValue:
    """reference: dygraph_grad_clip.py GradClipByValue."""

    def __init__(self, min_value, max_value):
        self.min_value = float(min_value)
        self.max_value = float(max_value)

    def __call__(self, params_grads):
        import jax.numpy as jnp

        return [
            (p, None if g is None else jnp.clip(g, self.min_value, self.max_value))
            for p, g in params_grads
        ]


class GradClipByNorm:
    """reference: dygraph_grad_clip.py GradClipByNorm — per-grad L2 cap."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        import jax.numpy as jnp

        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(g * g))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, g * scale))
        return out


class GradClipByGlobalNorm:
    """reference: dygraph_grad_clip.py GradClipByGlobalNorm."""

    def __init__(self, max_global_norm):
        self.max_global_norm = float(max_global_norm)

    def __call__(self, params_grads):
        import jax.numpy as jnp

        sq = [jnp.sum(g * g) for _, g in params_grads if g is not None]
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = jnp.minimum(
            self.max_global_norm / jnp.maximum(global_norm, 1e-12), 1.0)
        return [(p, None if g is None else g * scale) for p, g in params_grads]
