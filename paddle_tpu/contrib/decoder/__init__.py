"""Beam-search decoder API surface (reference: contrib/decoder/
beam_search_decoder.py — InitState/StateCell/TrainingDecoder/
BeamSearchDecoder built on the reference's While-op machinery).

The TPU-native decode path is ``paddle_tpu.decoding.beam_search`` — the
whole search compiled as one lax.scan (tests/test_seq2seq_decode.py);
these classes raise with that pointer instead of half-implementing the
While-op state-cell protocol."""
from __future__ import annotations

__all__ = ["InitState", "StateCell", "TrainingDecoder", "BeamSearchDecoder"]

_MSG = ("the While-op decoder protocol is replaced by the compiled "
        "whole-search paddle_tpu.decoding.beam_search / greedy_search "
        "(see tests/test_seq2seq_decode.py)")


class InitState:
    def __init__(self, *a, **k):
        raise NotImplementedError("InitState: " + _MSG)


class StateCell:
    def __init__(self, *a, **k):
        raise NotImplementedError("StateCell: " + _MSG)


class TrainingDecoder:
    def __init__(self, *a, **k):
        raise NotImplementedError("TrainingDecoder: " + _MSG)


class BeamSearchDecoder:
    def __init__(self, *a, **k):
        raise NotImplementedError("BeamSearchDecoder: " + _MSG)
