"""Decoder API (reference: contrib/decoder/beam_search_decoder.py —
InitState/StateCell/TrainingDecoder/BeamSearchDecoder built on the
reference's While-op + LoDTensorArray machinery; usage sample:
python/paddle/fluid/tests/test_beam_search_decoder.py).

TPU-native design: the same four model-facing classes, but
``TrainingDecoder`` lowers onto the compiled ``layers.DynamicRNN`` (one
lax.scan over the padded time axis) and ``BeamSearchDecoder.decode``
builds the static-lane While-loop search — fixed ``[B*beam]`` lanes,
per-step ``layers.beam_search`` selection with ``parent_idx`` state
gather (replacing the reference's LoD ``sequence_expand``), and
``layers.beam_search_decode`` backtracking the arrays into dense
``[B, beam, T]`` results.  The whole loop compiles into the program like
any other op; for the one-call functional form see
``paddle_tpu.decoding.beam_search``.
"""
from __future__ import annotations

import contextlib

import numpy as np

from paddle_tpu import layers

__all__ = ["InitState", "StateCell", "TrainingDecoder", "BeamSearchDecoder"]


class InitState(object):
    """Initial hidden state (reference: beam_search_decoder.py:43).

    Either wraps an existing ``init`` variable or creates a constant
    ``[batch, *shape]`` tensor batch-sized like ``init_boot``.  On the
    static-lane encoding ``need_reorder`` is recorded but moot — beam
    reordering is the explicit ``parent_idx`` gather in the decode loop,
    correct for any batch size.
    """

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                "init_boot must be provided to infer the shape of InitState"
            )
        else:
            tail = [int(s) for s in (shape or init_boot.shape[1:])]
            self._init = layers.fill_constant_batch_size_like(
                input=init_boot, shape=[-1] + tail, dtype=dtype,
                value=float(value),
            )
        self._shape = shape
        self._value = value
        self._need_reorder = need_reorder
        self._dtype = dtype

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class _MemoryState(object):
    """State backed by a DynamicRNN memory (reference:
    beam_search_decoder.py:100)."""

    def __init__(self, state_name, rnn_obj, init_state):
        self._state_name = state_name
        self._rnn_obj = rnn_obj
        self._state_mem = rnn_obj.memory(
            init=init_state.value, need_reorder=init_state.need_reorder
        )

    def get_state(self):
        return self._state_mem

    def update_state(self, state):
        self._rnn_obj.update_memory(self._state_mem, state)


class _LaneState(object):
    """State on the beam-search static lanes (replaces the reference's
    _ArrayState, beam_search_decoder.py:114): the decoder holds the
    current ``[B*beam, ...]`` value; committing stages the new value for
    the decoder to gather by ``parent_idx`` and array_write at the end
    of the step."""

    def __init__(self, state_name, decoder, init_state):
        self._state_name = state_name
        self._decoder = decoder
        self._cur = decoder._register_state(state_name, init_state)

    def get_state(self):
        return self._cur

    def update_state(self, state):
        self._decoder._stage_state(self._state_name, state)


class StateCell(object):
    """Hidden-state container + update rule for a decoder step
    (reference: beam_search_decoder.py:159).

    ``inputs``: dict name -> Variable (or None for step-provided inputs
    like the current word embedding); ``states``: dict name ->
    ``InitState``; ``out_state``: the state name whose value feeds the
    scoring layer.  Register the per-step recurrence with the
    ``@state_cell.state_updater`` decorator; inside it use
    ``get_input`` / ``get_state`` / ``set_state``.
    """

    def __init__(self, inputs, states, out_state, name=None):
        self._inputs = dict(inputs)
        self._init_states = dict(states)
        self._state_names = list(states)
        self._out_state_name = out_state
        if out_state not in self._init_states:
            raise ValueError("out_state %r is not a declared state" % out_state)
        self._updater = None
        self._cur_states = {}
        self._states_holder = {}   # state name -> {id(decoder): backing}
        self._cur_decoder_obj = None
        self._switched_decoder = False

    # -- decoder attach protocol (reference: _enter_decoder/_leave_decoder)
    def _enter_decoder(self, decoder_obj):
        if self._cur_decoder_obj is not None:
            raise ValueError("StateCell is already inside a decoder block")
        self._cur_decoder_obj = decoder_obj
        self._switched_decoder = False
        self._cur_states = {}

    def _leave_decoder(self, decoder_obj):
        if self._cur_decoder_obj is not decoder_obj:
            raise ValueError("leaving a decoder the StateCell never entered")
        self._cur_decoder_obj = None
        self._switched_decoder = False

    def _switch_decoder(self):
        """Lazily bind each declared state to the current decoder's
        backing (rnn memory / beam lanes) on first use inside the block."""
        if self._cur_decoder_obj is None:
            raise ValueError("StateCell must be used inside a decoder block")
        if self._switched_decoder:
            return
        dec = self._cur_decoder_obj
        for name in self._state_names:
            holder = self._states_holder.setdefault(name, {})
            if id(dec) not in holder:
                if isinstance(dec, TrainingDecoder):
                    holder[id(dec)] = _MemoryState(
                        name, dec._rnn, self._init_states[name]
                    )
                elif isinstance(dec, BeamSearchDecoder):
                    holder[id(dec)] = _LaneState(
                        name, dec, self._init_states[name]
                    )
                else:
                    raise ValueError("unknown decoder type %r" % type(dec))
            self._cur_states[name] = holder[id(dec)].get_state()
        self._switched_decoder = True

    # -- user surface
    def state_updater(self, updater):
        self._updater = updater
        return updater

    def get_input(self, input_name):
        if input_name not in self._inputs:
            raise ValueError("input %r not found in the StateCell" % input_name)
        val = self._inputs[input_name]
        if val is None:
            raise ValueError(
                "input %r has no bound value — pass it via "
                "compute_state(inputs={...})" % input_name
            )
        return val

    def get_state(self, state_name):
        if state_name not in self._init_states:
            raise ValueError("state %r not declared" % state_name)
        self._switch_decoder()
        return self._cur_states[state_name]

    def set_state(self, state_name, state_value):
        if state_name not in self._init_states:
            raise ValueError("state %r not declared" % state_name)
        self._cur_states[state_name] = state_value

    def compute_state(self, inputs):
        """Bind this step's inputs and run the registered updater."""
        if self._updater is None:
            raise ValueError(
                "no state updater registered — decorate one with "
                "@state_cell.state_updater"
            )
        self._switch_decoder()
        for name, value in inputs.items():
            if name not in self._inputs:
                raise ValueError("unknown input %r in compute_state" % name)
            self._inputs[name] = value
        self._updater(self)

    def update_states(self):
        """Commit the staged states back to the decoder's backing."""
        self._switch_decoder()
        dec = self._cur_decoder_obj
        for name in self._state_names:
            self._states_holder[name][id(dec)].update_state(
                self._cur_states[name]
            )

    def out_state(self):
        return self._cur_states[self._out_state_name]


class TrainingDecoder(object):
    """Teacher-forced decoder for training (reference:
    beam_search_decoder.py:384), lowered onto ``layers.DynamicRNN`` —
    the whole recurrence is one compiled lax.scan.

    ::

        decoder = TrainingDecoder(state_cell)
        with decoder.block():
            word = decoder.step_input(trg_embedding)
            decoder.state_cell.compute_state(inputs={'x': word})
            score = layers.fc(decoder.state_cell.get_state('h'),
                              size=V, act='softmax')
            decoder.state_cell.update_states()
            decoder.output(score)
        outputs = decoder()   # [B, T, V]

    ``seq_len`` (TPU-native extension): [B] int lengths of the padded
    target sequences; the reference reads them from the LoD.  When
    omitted, every row is assumed full length (dense padded batch).
    """

    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell, name=None, seq_len=None):
        self._name = name
        self._state_cell = state_cell
        self._status = TrainingDecoder.BEFORE_DECODER
        self._rnn = layers.DynamicRNN(name=name)
        self._seq_len = seq_len

    @contextlib.contextmanager
    def block(self):
        if self._status != TrainingDecoder.BEFORE_DECODER:
            raise ValueError("decoder.block() can only be entered once")
        self._status = TrainingDecoder.IN_DECODER
        self._state_cell._enter_decoder(self)
        with self._rnn.block():
            yield
        self._status = TrainingDecoder.AFTER_DECODER
        self._state_cell._leave_decoder(self)

    @property
    def state_cell(self):
        self._assert_in_decoder_block("state_cell")
        return self._state_cell

    @property
    def dynamic_rnn(self):
        return self._rnn

    def step_input(self, x):
        """Mark a [B, T, ...] sequence as a per-step input; returns the
        [B, ...] step slice."""
        self._assert_in_decoder_block("step_input")
        seq_len = self._seq_len
        if seq_len is None and self._rnn._seq_len is None:
            T = x.shape[1] if len(x.shape or ()) > 1 else None
            if T is None or int(T) < 0:
                raise ValueError(
                    "step_input needs seq_len= on the TrainingDecoder for "
                    "dynamic-length input %r" % x.name
                )
            # the lengths vector is read by the dynamic_rnn op in the
            # PARENT block, so build it there (we're inside the sub-block)
            from paddle_tpu import unique_name

            parent = self._rnn.sub_block.parent_block
            seq_len = parent.create_var(
                name=unique_name.generate("training_decoder_seq_len"),
                shape=[-1], dtype="int32",
            )
            parent.append_op(
                type="fill_constant_batch_size_like",
                inputs={"Input": [x]},
                outputs={"Out": [seq_len]},
                attrs={"shape": [-1], "value": float(int(T)),
                       "dtype": "int32", "input_dim_idx": 0,
                       "output_dim_idx": 0},
            )
        return self._rnn.step_input(x, seq_len=seq_len)

    def static_input(self, x):
        """Whole-sequence input visible unchanged at every step."""
        self._assert_in_decoder_block("static_input")
        return self._rnn.static_input(x)

    def output(self, *outputs):
        self._assert_in_decoder_block("output")
        self._rnn.output(*outputs)

    def __call__(self, *args, **kwargs):
        if self._status != TrainingDecoder.AFTER_DECODER:
            raise ValueError("decoder() called before its block completed")
        return self._rnn(*args, **kwargs)

    def _assert_in_decoder_block(self, method):
        if self._status != TrainingDecoder.IN_DECODER:
            raise ValueError(
                "%s should be invoked inside decoder.block()" % method
            )


class BeamSearchDecoder(object):
    """Beam-search decoder for inference (reference:
    beam_search_decoder.py:523).

    Static-lane TPU design: every source row keeps ``beam_size`` fixed
    lanes (``[B*beam]`` rows end to end) instead of the reference's
    shrinking LoD beams.  ``decode()`` builds a ``layers.While`` loop —
    per step: embed previous ids, run the StateCell, score with an
    fc+softmax to ``target_dict_dim``, select with ``layers.beam_search``
    (finished lanes persist via ``end_id`` masking), gather every state
    by ``parent_idx``, and array_write ids/scores/parents.  Calling the
    decoder returns ``(translation_ids [B, beam, T+1], translation_scores
    [B, beam])`` best-first via ``layers.beam_search_decode``.

    Feed contract (static lanes; see ``seed_init_feeds``): ``init_ids``
    is ``[B*beam, 1]`` int64 start tokens and ``init_scores`` is
    ``[B*beam, 1]`` float32 with lane 0 of each source at 0.0 and the
    other lanes at -1e9 (step 1 then expands from one live lane per
    source, matching the reference's single-seed LoD feed).

    TPU-native extensions: ``emb_param_attr`` / ``score_param_attr`` /
    ``score_bias_attr`` name the decode-side embedding / scoring weights
    so they can share trained parameters with the training program
    explicitly (the reference relies on unique-name counters lining up
    across programs).
    """

    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict=None, topk_size=50,
                 sparse_emb=True, max_len=100, beam_size=1, end_id=1,
                 name=None, emb_param_attr=None, score_param_attr=None,
                 score_bias_attr=None, batch_size=None):
        self._name = name
        self._state_cell = state_cell
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = int(target_dict_dim)
        self._word_dim = int(word_dim)
        self._input_var_dict = dict(input_var_dict or {})
        self._topk_size = int(topk_size)
        self._sparse_emb = sparse_emb
        self._max_len = int(max_len)
        self._beam_size = int(beam_size)
        self._end_id = int(end_id)
        self._emb_param_attr = emb_param_attr
        self._score_param_attr = score_param_attr
        self._score_bias_attr = score_bias_attr
        self._batch_size = batch_size
        self._status = BeamSearchDecoder.BEFORE_DECODER
        # populated while building the loop
        self._cur_states = {}      # state name -> in-loop current var
        self._staged_states = {}   # state name -> staged new var
        self._ids_array = None
        self._scores_array = None
        self._parents_array = None
        self._translation_ids = None
        self._translation_scores = None

    # ------------------------------------------------------------------
    @staticmethod
    def seed_init_feeds(batch_size, beam_size, start_id):
        """Numpy feed values for (init_ids, init_scores) on the static
        lanes: every lane starts at ``start_id``; lane 0 of each source
        scores 0, the rest -1e9."""
        bk = batch_size * beam_size
        ids = np.full((bk, 1), start_id, dtype="int64")
        scores = np.where(
            np.arange(bk) % beam_size == 0, 0.0, -1e9
        ).astype("float32").reshape(bk, 1)
        return ids, scores

    # -- _LaneState protocol -------------------------------------------
    def _register_state(self, name, init_state):
        if self._status != BeamSearchDecoder.IN_DECODER:
            raise ValueError("states bind inside decode()")
        return self._cur_states[name]

    def _stage_state(self, name, value):
        self._staged_states[name] = value

    # ------------------------------------------------------------------
    def _tile_to_lanes(self, v, nlanes):
        """[B, D...] -> [B*beam, D...] (each source row repeated beam
        times — the static analog of the reference's sequence_expand
        over the init LoD)."""
        K = self._beam_size
        shp = [int(s) for s in v.shape[1:]]
        expanded = layers.expand(
            layers.reshape(v, shape=[-1, 1] + shp), [1, K] + [1] * len(shp)
        )
        return layers.reshape(expanded, shape=[nlanes] + shp)

    def _nlanes(self):
        """Static lane count B*beam — XLA arrays need it at build time
        (the reference's LoD arrays are host-dynamic instead)."""
        if self._batch_size is not None:
            return int(self._batch_size) * self._beam_size
        ids_b = (self._init_ids.shape or [-1])[0]
        if ids_b is not None and int(ids_b) > 0:
            return int(ids_b)
        raise ValueError(
            "BeamSearchDecoder needs a static lane count: pass "
            "batch_size= (TPU-native extension; the compiled search "
            "needs static shapes) or give init_ids a static batch dim"
        )

    def decode(self):
        """Build the beam-search loop (reference:
        beam_search_decoder.py:653).  Override for a custom decoder."""
        if self._status != BeamSearchDecoder.BEFORE_DECODER:
            raise ValueError("decode() can only be called once")
        self._status = BeamSearchDecoder.IN_DECODER
        cell = self._state_cell
        cell._enter_decoder(self)
        K = self._beam_size
        ML = self._max_len

        init_states = {n: cell._init_states[n] for n in cell._state_names}
        counter = layers.zeros(shape=[1], dtype="int64")
        array_len = layers.fill_constant([1], "int64", ML)
        nlanes = self._nlanes()
        state0 = {
            n: self._tile_to_lanes(s.value, nlanes)
            for n, s in init_states.items()
        }
        ids0 = layers.reshape(self._init_ids, shape=[nlanes, 1])
        scores0 = layers.reshape(self._init_scores, shape=[nlanes, 1])
        lane_inputs = {
            name: self._tile_to_lanes(var, nlanes)
            for name, var in self._input_var_dict.items()
        }
        for name in lane_inputs:
            if name not in cell._inputs:
                raise ValueError("Variable %s not found in StateCell" % name)

        arrays = {}
        for n, v in state0.items():
            arr = layers.create_array(
                ML + 1, [int(s) for s in v.shape], dtype=v.dtype
            )
            arrays[n] = layers.array_write(v, counter, arr)
        ids_arr = layers.array_write(
            ids0, counter, layers.create_array(ML + 1, [nlanes, 1], "int64")
        )
        score_arr = layers.array_write(
            scores0, counter,
            layers.create_array(ML + 1, [nlanes, 1], "float32"),
        )
        parent_arr = layers.create_array(ML + 1, [nlanes], "int32")

        cond = layers.less_than(counter, array_len)
        loop = layers.While(cond, max_trip_count=ML)
        with loop.block():
            # reshape pins static element shapes on the array reads
            # (shape inference inside a While sub-block is deferred)
            prev_ids = layers.reshape(
                layers.array_read(ids_arr, counter), shape=[nlanes, 1]
            )
            prev_scores = layers.reshape(
                layers.array_read(score_arr, counter), shape=[nlanes, 1]
            )
            self._cur_states = {
                n: layers.reshape(
                    layers.array_read(arrays[n], counter),
                    shape=[int(s) for s in state0[n].shape],
                )
                for n in init_states
            }
            self._staged_states = {}
            prev_ids_embedding = layers.reshape(
                layers.embedding(
                    prev_ids,
                    size=[self._target_dict_dim, self._word_dim],
                    dtype="float32",
                    is_sparse=self._sparse_emb,
                    param_attr=self._emb_param_attr,
                ),
                shape=[nlanes, self._word_dim],
            )

            feed_dict = dict(lane_inputs)
            for input_name in cell._inputs:
                if input_name not in feed_dict:
                    feed_dict[input_name] = prev_ids_embedding

            cell.compute_state(inputs=feed_dict)
            current_state = cell.out_state()
            scores = layers.fc(
                current_state,
                size=self._target_dict_dim,
                act="softmax",
                param_attr=self._score_param_attr,
                bias_attr=self._score_bias_attr,
            )
            topk_scores, topk_indices = layers.topk(
                scores, k=min(self._topk_size, self._target_dict_dim)
            )
            accu_scores = layers.elementwise_add(
                layers.log(topk_scores), layers.reshape(prev_scores, [-1, 1])
            )
            sel_ids, sel_scores, parent = layers.beam_search(
                prev_ids, prev_scores, topk_indices, accu_scores,
                K, end_id=self._end_id, return_parent_idx=True,
            )

            cell.update_states()
            layers.increment(counter, value=1, in_place=True)
            # beam reorder = explicit parent gather (the reference's
            # sequence_expand over LoD), then persist for the next step
            for n in init_states:
                new_state = self._staged_states.get(n, self._cur_states[n])
                layers.array_write(
                    layers.gather(new_state, parent), counter, arrays[n]
                )
            layers.array_write(sel_ids, counter, ids_arr)
            layers.array_write(sel_scores, counter, score_arr)
            layers.array_write(parent, counter, parent_arr)
            layers.less_than(counter, array_len, cond=cond)

        self._ids_array = ids_arr
        self._scores_array = score_arr
        self._parents_array = parent_arr
        self._translation_ids, self._translation_scores = (
            layers.beam_search_decode(
                ids_arr, score_arr, beam_size=K, end_id=self._end_id,
                parents=parent_arr,
            )
        )
        self._status = BeamSearchDecoder.AFTER_DECODER
        cell._leave_decoder(self)

    def __call__(self):
        if self._status != BeamSearchDecoder.AFTER_DECODER:
            raise ValueError("decoder() must follow decode()")
        return self._translation_ids, self._translation_scores

    @property
    def state_cell(self):
        return self._state_cell
