"""Memory-usage estimator (reference: contrib/memory_usage_calc.py —
sums var element sizes, batch dim substituted, returns a (low, high)
estimate range in the requested unit)."""
from __future__ import annotations

import numpy as np

__all__ = ["memory_usage"]

_DTYPE_BYTES = {"float16": 2, "bfloat16": 2, "float32": 4, "float64": 8,
                "int8": 1, "int16": 2, "int32": 4, "int64": 8, "uint8": 1,
                "bool": 1}


def memory_usage(program, batch_size=1, unit="MB"):
    """Estimate activation+parameter memory for one iteration.  Returns
    (low, high) in ``unit`` — the reference brackets its estimate with
    +/-30% for workspace variance; XLA fusion usually lands below the
    low bound, so treat this as the reference-comparable ceiling."""
    div = {"B": 1, "KB": 1 << 10, "MB": 1 << 20, "GB": 1 << 30}[unit]
    total = 0
    for var in program.list_vars():
        if var.shape is None:
            continue
        n = 1
        for s in var.shape:
            n *= batch_size if int(s) == -1 else int(s)
        total += n * _DTYPE_BYTES.get(str(var.dtype), 4)
    est = total / div
    return est * 0.7, est * 1.3
