"""Model summary (reference: contrib/model_stat.py summary — per-layer
param counts + FLOPs table printed for a Program)."""
from __future__ import annotations

import numpy as np

__all__ = ["summary"]


def summary(main_prog, batch_size=1):
    """Print a param/FLOPs table; returns (total_params, total_flops)."""
    from paddle_tpu.contrib.slim.nas import program_flops

    total_params = 0
    rows = []
    for p in main_prog.all_parameters():
        n = int(np.prod([abs(int(s)) for s in p.shape]))
        total_params += n
        rows.append((p.name, tuple(p.shape), n))
    flops = program_flops(main_prog)
    print("%-40s %-20s %s" % ("param", "shape", "count"))
    for name, shape, n in rows:
        print("%-40s %-20s %d" % (name, shape, n))
    print("total params: %d (%.2f M)" % (total_params, total_params / 1e6))
    print("total FLOPs (matmul/conv, batch=%d): %.3f GFLOPs"
          % (batch_size, flops / 1e9))
    return total_params, flops
