"""Op-frequency statistics (reference: contrib/op_frequence.py
op_freq_statistic — op-type histogram plus adjacent-pair counts)."""
from __future__ import annotations

from collections import Counter

__all__ = ["op_freq_statistic"]


def op_freq_statistic(program):
    """Returns (single_op_counter, pair_op_counter) over all blocks."""
    singles, pairs = Counter(), Counter()
    for block in program.blocks:
        prev = None
        for op in block.ops:
            singles[op.type] += 1
            if prev is not None:
                pairs[(prev, op.type)] += 1
            prev = op.type
    return singles, pairs
