"""Distributed reader decorator (reference: contrib/reader/
distributed_reader.py — each trainer yields its 1/Nth slice by
PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM)."""
from __future__ import annotations

import os

__all__ = ["distributed_batch_reader"]


def distributed_batch_reader(batch_reader):
    trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    trainers = int(os.environ.get("PADDLE_TRAINERS_NUM",
                                  os.environ.get("PADDLE_TRAINERS", "1")))

    def decorated():
        for i, batch in enumerate(batch_reader()):
            if i % trainers == trainer_id:
                yield batch

    return decorated
