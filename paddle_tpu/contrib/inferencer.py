"""reference: contrib/inferencer.py — re-export (the implementation
lives beside Trainer in contrib/trainer.py)."""
from paddle_tpu.contrib.trainer import Inferencer  # noqa: F401

__all__ = ["Inferencer"]
