"""contrib utils (reference: contrib/utils/ — HDFSClient over the
hadoop CLI + lookup-table checkpoint helpers).

HDFSClient delegates to io_fs's hadoop-CLI shim; the lookup-table
helpers operate on the sparse PS via PSClient.save (the reference
mutates pserver checkpoint dirs on disk)."""
from __future__ import annotations

__all__ = ["HDFSClient", "load_persistables_for_increment",
           "load_persistables_for_inference"]


class HDFSClient:
    """reference: contrib/utils/hdfs_utils.py HDFSClient — thin verbs
    over the hadoop CLI (io_fs implements the subprocess plumbing)."""

    def __init__(self, hadoop_home=None, configs=None):
        from paddle_tpu import io_fs

        self._fs = io_fs

    def is_exist(self, path):
        return self._fs.fs_exists(path)

    def is_dir(self, path):
        try:
            self._fs.fs_ls(path)
            return True
        except Exception:  # noqa: BLE001 — CLI error = not a dir
            return False

    def delete(self, path):
        return self._fs.fs_rm(path)

    def upload(self, hdfs_path, local_path, overwrite=False, retry_times=5):
        with open(local_path, "rb") as src,                 self._fs.open_write(hdfs_path, "wb") as dst:
            dst.write(src.read())

    def download(self, hdfs_path, local_path, overwrite=False, retry_times=5):
        # raw bytes (the reference downloads via -get): the .gz read
        # converter must NOT decompress into a .gz-named local copy
        with self._fs.open_read(hdfs_path, "rb", raw=True) as src, \
                open(local_path, "wb") as dst:
            dst.write(src.read())

    def ls(self, path):
        return self._fs.fs_ls(path)

    def lsr(self, path):
        return self._fs.fs_ls(path)

    def make_local_dirs(self, local_path):
        import os

        os.makedirs(local_path, exist_ok=True)

    def makedirs(self, path):
        return self._fs.fs_mkdir(path)

    def rename(self, src, dst):
        return self._fs.fs_mv(src, dst)


def load_persistables_for_increment(dirname, executor, program,
                                    lookup_table_var=None,
                                    lookup_table_var_path=None):
    """reference: contrib/utils/lookup_table_utils.py — resume training:
    dense persistables from the checkpoint dir + sparse rows back onto
    the PS (program._ps_client.push from the saved (ids, rows))."""
    import numpy as np

    from paddle_tpu import io as io_mod

    io_mod.load_persistables(executor, dirname, main_program=program)
    client = getattr(program, "_ps_client", None)
    if client is not None and lookup_table_var_path:
        data = np.load(lookup_table_var_path, allow_pickle=False)
        client.push_sparse(lookup_table_var, data["ids"], data["rows"])


def load_persistables_for_inference(dirname, executor, program,
                                    lookup_table_var_name=None):
    """reference: lookup_table_utils.py — inference: dense persistables
    only; distributed lookups must be bound to a serving PS."""
    from paddle_tpu import io as io_mod

    io_mod.load_persistables(executor, dirname, main_program=program)
