"""contrib utils (reference: contrib/utils/ — HDFSClient over the
hadoop CLI + lookup-table checkpoint helpers).

HDFSClient delegates to io_fs's hadoop-CLI shim; the lookup-table
helpers operate on the sparse PS via PSClient.save (the reference
mutates pserver checkpoint dirs on disk)."""
from __future__ import annotations

__all__ = ["HDFSClient", "load_persistables_for_increment",
           "load_persistables_for_inference"]


class HDFSClient:
    """reference: contrib/utils/hdfs_utils.py HDFSClient — thin verbs
    over the hadoop CLI (io_fs implements the subprocess plumbing)."""

    def __init__(self, hadoop_home=None, configs=None):
        from paddle_tpu import io_fs

        self._fs = io_fs

    def is_exist(self, path):
        return self._fs.fs_exists(path)

    def is_dir(self, path):
        try:
            self._fs.fs_ls(path)
            return True
        except Exception:  # noqa: BLE001 — CLI error = not a dir
            return False

    def delete(self, path):
        return self._fs.fs_rm(path)

    def upload(self, hdfs_path, local_path, overwrite=False, retry_times=5):
        if not overwrite and self.is_exist(hdfs_path):
            # reference semantics: hadoop -put fails on an existing
            # destination unless the caller asked to overwrite
            raise FileExistsError(
                "upload: %s exists (pass overwrite=True to replace)"
                % hdfs_path
            )
        with open(local_path, "rb") as src,                 self._fs.open_write(hdfs_path, "wb") as dst:
            dst.write(src.read())

    def download(self, hdfs_path, local_path, overwrite=False, retry_times=5):
        # raw bytes (the reference downloads via -get): the .gz read
        # converter must NOT decompress into a .gz-named local copy
        with self._fs.open_read(hdfs_path, "rb", raw=True) as src, \
                open(local_path, "wb") as dst:
            dst.write(src.read())

    def ls(self, path):
        return self._fs.fs_ls(path)

    def lsr(self, path):
        return self._fs.fs_ls(path)

    def make_local_dirs(self, local_path):
        import os

        os.makedirs(local_path, exist_ok=True)

    def makedirs(self, path):
        return self._fs.fs_mkdir(path)

    def rename(self, src, dst):
        return self._fs.fs_mv(src, dst)


def load_persistables_for_increment(dirname, executor, program,
                                    lookup_table_var=None,
                                    lookup_table_var_path=None):
    """reference: contrib/utils/lookup_table_utils.py — resume training:
    dense persistables from the checkpoint dir + sparse rows back onto
    the PS (program._ps_client.push from the saved (ids, rows))."""
    import numpy as np

    from paddle_tpu import io as io_mod

    io_mod.load_persistables(executor, dirname, main_program=program)
    client = getattr(program, "_ps_client", None)
    if client is not None and lookup_table_var_path:
        data = np.load(lookup_table_var_path, allow_pickle=False)
        client.push_sparse(lookup_table_var, data["ids"], data["rows"])


def load_persistables_for_inference(dirname, executor, program,
                                    lookup_table_var_name=None):
    """reference: lookup_table_utils.py — inference: dense persistables
    only; distributed lookups must be bound to a serving PS."""
    from paddle_tpu import io as io_mod

    io_mod.load_persistables(executor, dirname, main_program=program)


def convert_dist_to_sparse_program(program):
    """reference: lookup_table_utils.py:85 — prepare a
    distributed-lookup-table program for sparse (PS-side) storage.

    TPU-native mapping: a table built with
    ``layers.embedding(is_distributed=True)`` is ALREADY sparse on the
    parameter server (distributed_lookup_table ops + per-program
    metadata).  This helper (re)builds that metadata from the op graph —
    the case that matters is a Program that lost its side-channel dict
    (e.g. constructed by an older serializer); table heights are read
    from the recorded metadata when present, else left at the reference
    default of 0 meaning 'server decides'.  A program with only dense
    ``lookup_table`` ops raises with guidance (build with
    ``is_distributed=True``; there is no after-the-fact dense->sparse
    rewrite on this architecture)."""
    block = program.global_block()
    dist_ops = [op for op in block.ops
                if op.type == "distributed_lookup_table"]
    if not dist_ops:
        raise ValueError(
            "convert_dist_to_sparse_program: no distributed lookup "
            "tables in this program — build the embedding with "
            "layers.embedding(..., is_distributed=True) (the sparse "
            "PS-backed form; see distributed/ps.py)"
        )
    tables = dict(getattr(program, "_distributed_tables", {}) or {})
    for op in dist_ops:
        rows_name = op.inputs["Rows"][0]
        if rows_name in tables:
            continue
        rows_var = block._find_var_recursive(rows_name)
        ids_name = op.inputs["OrigIds"][0]
        ids_var = block._find_var_recursive(ids_name)
        ids_shape = tuple(ids_var.shape or ()) if ids_var is not None else ()
        tables[rows_name] = {
            "table": op.attrs["table"],
            "dim": int(rows_var.shape[-1]) if rows_var is not None else 0,
            "height": 0,  # server decides; exact height only via metadata
            "ids_name": ids_name,
            "rows_name": rows_name,
            "local_name": op.inputs["Ids"][0],
            "squeeze_last": bool(ids_shape and ids_shape[-1] == 1),
        }
    program._distributed_tables = tables
    return program


def multi_download(client, hdfs_path, local_path, trainer_id, trainers,
                   multi_processes=5):
    """reference: hdfs_utils.py:437 — download this trainer's round-robin
    shard of the FILES under ``hdfs_path`` (directories are skipped, as
    the reference's lsr(only_file=True) does) concurrently; returns the
    local paths."""
    import concurrent.futures
    import os

    from paddle_tpu import io_fs

    os.makedirs(local_path, exist_ok=True)
    files = io_fs.fs_ls(hdfs_path, files_only=True)
    shard = io_fs.file_shard(files, trainer_id, trainers)

    def fetch(src):
        dst = os.path.join(local_path, os.path.basename(src))
        client.download(src, dst)
        return dst

    with concurrent.futures.ThreadPoolExecutor(max_workers=multi_processes) as ex:
        return list(ex.map(fetch, shard))


def multi_upload(client, hdfs_path, local_path, multi_processes=5,
                 overwrite=False, sync=True):
    """reference: hdfs_utils.py:508 — upload every file under
    ``local_path`` concurrently (destination dirs created once, before
    the pool — not one mkdir subprocess per file).

    ``overwrite=False`` keeps existing destination files: the colliding
    upload raises FileExistsError (per-file; other files still upload).
    ``sync=False`` returns immediately with a list of futures (call
    ``.result()`` to join); ``sync=True`` blocks and returns the
    uploaded relative paths."""
    import concurrent.futures
    import os

    files = []
    parents = set()
    for root, _, names in os.walk(local_path):
        for n in names:
            src = os.path.join(root, n)
            files.append(src)
            rel_dir = os.path.relpath(root, local_path)
            dst_dir = hdfs_path.rstrip("/")
            if rel_dir != ".":
                dst_dir += "/" + rel_dir
            parents.add(dst_dir)
    for p in sorted(parents) or [hdfs_path]:
        client.makedirs(p)

    def put(src):
        rel = os.path.relpath(src, local_path)
        client.upload(hdfs_path.rstrip("/") + "/" + rel, src,
                      overwrite=overwrite)
        return rel

    ex = concurrent.futures.ThreadPoolExecutor(max_workers=multi_processes)
    futures = [ex.submit(put, f) for f in files]
    if not sync:
        ex.shutdown(wait=False)
        return futures
    try:
        return [f.result() for f in futures]
    finally:
        ex.shutdown(wait=True)


__all__ += ["convert_dist_to_sparse_program", "multi_download",
            "multi_upload"]
