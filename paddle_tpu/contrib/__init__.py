"""Contrib namespace (reference: python/paddle/fluid/contrib/)."""
from paddle_tpu.contrib import mixed_precision  # noqa: F401
from paddle_tpu.contrib import slim  # noqa: F401
from paddle_tpu.contrib import float16  # noqa: F401,E402
