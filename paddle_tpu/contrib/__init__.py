"""Contrib namespace (reference: python/paddle/fluid/contrib/)."""
from paddle_tpu.contrib import mixed_precision  # noqa: F401
from paddle_tpu.contrib import slim  # noqa: F401
from paddle_tpu.contrib import float16  # noqa: F401,E402
from paddle_tpu.contrib import memory_usage_calc  # noqa: F401,E402
from paddle_tpu.contrib import model_stat  # noqa: F401,E402
from paddle_tpu.contrib import op_frequence  # noqa: F401,E402
from paddle_tpu.contrib import extend_optimizer  # noqa: F401,E402
from paddle_tpu.contrib import quantize  # noqa: F401,E402
from paddle_tpu.contrib import reader  # noqa: F401,E402
from paddle_tpu.contrib import utils  # noqa: F401,E402
from paddle_tpu.contrib import decoder  # noqa: F401,E402
from paddle_tpu.contrib import layers  # noqa: F401,E402
from paddle_tpu.contrib import trainer  # noqa: F401,E402
from paddle_tpu.contrib import inferencer  # noqa: F401,E402
from paddle_tpu.contrib.memory_usage_calc import memory_usage  # noqa: F401,E402
from paddle_tpu.contrib.op_frequence import op_freq_statistic  # noqa: F401,E402
from paddle_tpu.contrib.model_stat import summary  # noqa: F401,E402
# star-level re-exports matching the reference contrib/__init__.py
# (from .decoder import * / from .quantize import *)
from paddle_tpu.contrib.decoder import (  # noqa: F401,E402
    BeamSearchDecoder,
    InitState,
    StateCell,
    TrainingDecoder,
)
from paddle_tpu.contrib.quantize import QuantizeTranspiler  # noqa: F401,E402
