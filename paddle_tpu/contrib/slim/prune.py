"""Magnitude pruning (reference: python/paddle/fluid/contrib/slim/prune/
— Pruner/SensitivePruner over IrGraph; here a scope-level magnitude
pruner plus an in-graph mask so pruned weights STAY zero through
training updates).

``Pruner.prune(program, scope, params, ratios)``:
* computes a per-param magnitude mask at the requested sparsity ratio,
* zeroes the scope value,
* appends a ``elementwise_mul`` with a persistable mask var right after
  each parameter's optimizer update, so subsequent steps cannot
  resurrect pruned weights (the reference applies masks inside its
  Pruner the same way conceptually — mask * param).
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

__all__ = ["Pruner"]


class Pruner:
    def __init__(self, criterion: str = "l1_norm"):
        if criterion not in ("l1_norm", "abs"):
            raise ValueError("unsupported criterion %r" % criterion)
        self.criterion = criterion

    def prune(self, program, scope, params: Sequence[str], ratios: Sequence[float],
              place=None, lazy: bool = False, only_graph: bool = False):
        """Mask the smallest-|w| fraction ``ratio`` of each param.
        Returns {param: actual_sparsity}."""
        from paddle_tpu import unique_name
        import jax.numpy as jnp

        block = program.global_block()
        result: Dict[str, float] = {}
        for name, ratio in zip(params, ratios):
            val = np.asarray(scope.get(name))
            k = int(round(val.size * float(ratio)))
            mask = np.ones(val.shape, np.float32)
            if k > 0:
                # zero EXACTLY k entries (a magnitude-threshold test
                # over-prunes when values tie at the k-th magnitude)
                idx = np.argsort(np.abs(val).ravel(), kind="stable")[:k]
                flat = mask.ravel()
                flat[idx] = 0.0
                mask = flat.reshape(val.shape)
            # NO startup initializer: the mask value is written to the
            # scope directly (an initializer in a startup program
            # would re-set it to ones on re-init, resurrecting pruned
            # weights — and pollute an unrelated default startup).
            # After re-initializing a fresh scope, call prune() again.
            mask_var = block.create_var(
                name=unique_name.generate(name + "@PRUNE_MASK@"),
                shape=list(val.shape), dtype="float32",
                persistable=True, stop_gradient=True,
            )
            scope.set(mask_var.name, jnp.asarray(mask))
            if not only_graph:
                scope.set(name, jnp.asarray(val * mask))
            # re-apply the mask after every update of this param: find
            # the LAST op writing it and insert mul right after
            last_idx = None
            for i, op in enumerate(block.ops):
                if name in op.output_arg_names:
                    last_idx = i
            if last_idx is not None:
                block._insert_op(
                    last_idx + 1,
                    type="elementwise_mul",
                    inputs={"X": [name], "Y": [mask_var.name]},
                    outputs={"Out": [name]},
                    attrs={"op_role": "optimize", "__prune_mask_for__": name},
                )
            result[name] = 1.0 - float(mask.mean())
        # record what was pruned: an op appended AFTER the mask op that
        # writes a pruned param would silently resurrect zeroed weights
        # (ADVICE r2) — _check_no_late_writers catches it at next use
        pruned = getattr(program, "_pruned_params", None) or {}
        pruned.update(result)
        program._pruned_params = pruned
        program.version += 1
        return result


def _check_no_late_writers(program) -> None:
    """Raise if any op writes a pruned param after its mask re-apply op
    (prune() must be the final mutation of a pruned param's writers)."""
    pruned = getattr(program, "_pruned_params", None)
    if not pruned:
        return
    for block in program.blocks:
        mask_pos = {}
        for i, op in enumerate(block.ops):
            tgt = op.attrs.get("__prune_mask_for__")
            if tgt is not None:
                mask_pos[tgt] = i
        for i, op in enumerate(block.ops):
            if op.attrs.get("__prune_mask_for__") is not None:
                continue
            for name in op.output_arg_names:
                if name in mask_pos and i > mask_pos[name]:
                    raise RuntimeError(
                        "op %r (index %d) writes pruned param %r after its "
                        "prune-mask op (index %d) — the write would "
                        "resurrect pruned weights; call prune() again "
                        "after the last program mutation"
                        % (op.type, i, name, mask_pos[name])
                    )
