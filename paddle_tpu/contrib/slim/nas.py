"""Neural-architecture search controller (reference: python/paddle/fluid/
contrib/slim/nas/ — light_nas_strategy.py + the simulated-annealing
controller in controller.py / sa_controller).

Pure host-side search logic: tokens index a ``range_table`` of per-slot
choice counts; ``next_tokens`` perturbs the best-so-far, ``update``
accepts by the Metropolis criterion with geometric temperature decay.
Model construction from tokens is the user's search space function, as
in the reference.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["SAController", "SearchSpace", "SANAS", "program_flops"]


class SAController:
    def __init__(self, range_table: Sequence[int], reduce_rate: float = 0.85,
                 init_temperature: float = 1024.0, max_try_times: int = 300,
                 init_tokens: Optional[Sequence[int]] = None, seed: int = 0):
        self._range_table = [int(r) for r in range_table]
        self._reduce_rate = float(reduce_rate)
        self._temperature = float(init_temperature)
        self._max_try_times = int(max_try_times)
        self._rng = np.random.RandomState(seed)
        self._tokens = (
            [int(t) for t in init_tokens]
            if init_tokens is not None
            else [int(self._rng.randint(0, r)) for r in self._range_table]
        )
        self._reward = -float("inf")
        self.best_tokens = list(self._tokens)
        self.max_reward = -float("inf")
        self._iter = 0

    @property
    def current_tokens(self) -> List[int]:
        return list(self._tokens)

    def next_tokens(self, control_token: Optional[Sequence[int]] = None,
                    constraint=None) -> List[int]:
        """Perturb one random slot of the current tokens.  With a
        ``constraint(tokens) -> bool`` (e.g. a FLOPs budget), resample up
        to ``max_try_times`` until it holds (reference sa_controller
        retry loop)."""
        for _ in range(self._max_try_times):
            base = list(control_token) if control_token is not None else list(self._tokens)
            idx = int(self._rng.randint(0, len(base)))
            base[idx] = int(self._rng.randint(0, self._range_table[idx]))
            if constraint is None or constraint(base):
                return base
        raise RuntimeError(
            "no tokens satisfying the constraint in %d tries" % self._max_try_times
        )

    def update(self, tokens: Sequence[int], reward: float) -> bool:
        """Metropolis accept/reject; returns True when accepted.  Also
        tracks the best-ever (tokens, reward)."""
        self._iter += 1
        self._temperature *= self._reduce_rate
        reward = float(reward)
        accept = reward > self._reward or self._rng.uniform() < math.exp(
            min(0.0, (reward - self._reward)) / max(self._temperature, 1e-9)
        )
        if accept:
            self._tokens = list(tokens)
            self._reward = reward
        if reward > self.max_reward:
            self.max_reward = reward
            self.best_tokens = list(tokens)
        return bool(accept)


class SearchSpace:
    """Architecture search space (reference: contrib/slim/nas/
    search_space.py — init_tokens / range_table / create_net contract).

    ``create_net(tokens)`` must return
    ``(startup_program, train_program, eval_program, train_fetches,
    eval_fetches)`` where fetches are lists of Variables; the FIRST
    train fetch is minimized-loss-like (logged) and the FIRST eval fetch
    is the reward metric (higher is better).
    """

    def init_tokens(self) -> List[int]:
        raise NotImplementedError("Abstract method.")

    def range_table(self) -> List[int]:
        raise NotImplementedError("Abstract method.")

    def create_net(self, tokens: Sequence[int]):
        raise NotImplementedError("Abstract method.")


def program_flops(program) -> int:
    """Rough FLOPs of a Program's matmul/conv ops (for NAS constraints —
    reference: light_nas_strategy.py target_flops on GraphWrapper)."""
    total = 0
    for op in program.global_block().ops:
        try:
            if op.type in ("mul", "matmul"):
                x = program.global_block().var(op.inputs["X"][0])
                y = program.global_block().var(op.inputs["Y"][0])
                if x.shape and y.shape:
                    m = int(np.prod([abs(int(s)) for s in x.shape[:-1]]))
                    k = abs(int(x.shape[-1]))
                    n = abs(int(y.shape[-1]))
                    total += 2 * m * k * n
            elif op.type == "conv2d":
                w = program.global_block().var(op.inputs["Filter"][0])
                out = program.global_block().var(op.outputs["Output"][0])
                if w.shape and out.shape:
                    per_out = 2 * int(np.prod([int(s) for s in w.shape[1:]]))
                    total += per_out * int(np.prod([abs(int(s)) for s in out.shape]))
        except (KeyError, ValueError, TypeError):
            continue
    return total


class SANAS:
    """Simulated-annealing NAS driver (reference: contrib/slim/nas/ —
    light_nas_strategy.py's controller loop + sa_nas in later releases):
    actually BUILDS, TRAINS, and EVALUATES each candidate program the
    controller proposes, then feeds the reward back.

    Either drive it manually (``next_archs()`` ... ``reward(score)``) or
    call ``search(train_feeds, eval_feeds, ...)`` for the full loop.
    """

    def __init__(self, search_space: SearchSpace, search_steps: int = 10,
                 reduce_rate: float = 0.85, init_temperature: float = 1024.0,
                 constraint=None, seed: int = 0):
        self.space = search_space
        self.steps = int(search_steps)
        self._constraint = constraint
        self.controller = SAController(
            search_space.range_table(),
            reduce_rate=reduce_rate,
            init_temperature=init_temperature,
            init_tokens=search_space.init_tokens(),
            seed=seed,
        )
        self._pending: Optional[List[int]] = None
        self.history: List[dict] = []

    # -- manual protocol (reference: search_agent.py next_tokens/reward) --
    def next_archs(self) -> List[int]:
        self._pending = self.controller.next_tokens(constraint=self._constraint)
        return list(self._pending)

    def reward(self, score: float) -> bool:
        if self._pending is None:
            raise RuntimeError("reward() without next_archs()")
        accepted = self.controller.update(self._pending, score)
        self.history.append(
            {"tokens": list(self._pending), "reward": float(score),
             "accepted": bool(accepted)}
        )
        self._pending = None
        return accepted

    @property
    def best_tokens(self) -> List[int]:
        return list(self.controller.best_tokens)

    @property
    def max_reward(self) -> float:
        return float(self.controller.max_reward)

    # -- full search loop --
    def search(self, train_feeds: Sequence[dict], eval_feeds: Sequence[dict],
               train_epochs: int = 1, place=None) -> List[int]:
        """For each controller proposal: build the candidate via
        ``space.create_net(tokens)``, train it ``train_epochs`` passes
        over ``train_feeds``, evaluate the first eval fetch averaged
        over ``eval_feeds`` as the reward, update the controller.
        Returns the best tokens found."""
        from paddle_tpu import executor as executor_mod
        from paddle_tpu.executor import Executor
        from paddle_tpu.framework import CPUPlace
        from paddle_tpu.scope import Scope, scope_guard

        place = place or CPUPlace()
        exe = Executor(place)
        for _ in range(self.steps):
            tokens = self.next_archs()
            startup, train_prog, eval_prog, train_f, eval_f = (
                self.space.create_net(tokens)
            )
            scope = Scope()
            with scope_guard(scope):
                exe.run(startup)
                for _ in range(int(train_epochs)):
                    for feed in train_feeds:
                        exe.run(train_prog, feed=feed,
                                fetch_list=list(train_f))
                scores = []
                for feed in eval_feeds:
                    vals = exe.run(eval_prog, feed=feed,
                                   fetch_list=list(eval_f))
                    scores.append(float(np.asarray(vals[0])))
            self.reward(float(np.mean(scores)))
        return self.best_tokens
