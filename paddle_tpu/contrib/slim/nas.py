"""Neural-architecture search controller (reference: python/paddle/fluid/
contrib/slim/nas/ — light_nas_strategy.py + the simulated-annealing
controller in controller.py / sa_controller).

Pure host-side search logic: tokens index a ``range_table`` of per-slot
choice counts; ``next_tokens`` perturbs the best-so-far, ``update``
accepts by the Metropolis criterion with geometric temperature decay.
Model construction from tokens is the user's search space function, as
in the reference.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["SAController"]


class SAController:
    def __init__(self, range_table: Sequence[int], reduce_rate: float = 0.85,
                 init_temperature: float = 1024.0, max_try_times: int = 300,
                 init_tokens: Optional[Sequence[int]] = None, seed: int = 0):
        self._range_table = [int(r) for r in range_table]
        self._reduce_rate = float(reduce_rate)
        self._temperature = float(init_temperature)
        self._max_try_times = int(max_try_times)
        self._rng = np.random.RandomState(seed)
        self._tokens = (
            [int(t) for t in init_tokens]
            if init_tokens is not None
            else [int(self._rng.randint(0, r)) for r in self._range_table]
        )
        self._reward = -float("inf")
        self.best_tokens = list(self._tokens)
        self.max_reward = -float("inf")
        self._iter = 0

    @property
    def current_tokens(self) -> List[int]:
        return list(self._tokens)

    def next_tokens(self, control_token: Optional[Sequence[int]] = None,
                    constraint=None) -> List[int]:
        """Perturb one random slot of the current tokens.  With a
        ``constraint(tokens) -> bool`` (e.g. a FLOPs budget), resample up
        to ``max_try_times`` until it holds (reference sa_controller
        retry loop)."""
        for _ in range(self._max_try_times):
            base = list(control_token) if control_token is not None else list(self._tokens)
            idx = int(self._rng.randint(0, len(base)))
            base[idx] = int(self._rng.randint(0, self._range_table[idx]))
            if constraint is None or constraint(base):
                return base
        raise RuntimeError(
            "no tokens satisfying the constraint in %d tries" % self._max_try_times
        )

    def update(self, tokens: Sequence[int], reward: float) -> bool:
        """Metropolis accept/reject; returns True when accepted.  Also
        tracks the best-ever (tokens, reward)."""
        self._iter += 1
        self._temperature *= self._reduce_rate
        reward = float(reward)
        accept = reward > self._reward or self._rng.uniform() < math.exp(
            min(0.0, (reward - self._reward)) / max(self._temperature, 1e-9)
        )
        if accept:
            self._tokens = list(tokens)
            self._reward = reward
        if reward > self.max_reward:
            self.max_reward = reward
            self.best_tokens = list(tokens)
        return bool(accept)
