"""Model compression (reference: python/paddle/fluid/contrib/slim/ —
quantization QAT passes, distillation, pruning, NAS).  Round-1 surface:
quantization-aware training rewrite; the rest of slim is tracked in
SURVEY.md §2.9 as open parity items."""
from paddle_tpu.contrib.slim import quantization  # noqa: F401
