"""Model compression (reference: python/paddle/fluid/contrib/slim/ —
quantization QAT passes, distillation, pruning, NAS).  Surface:
quantization-aware training rewrite, magnitude pruning with in-graph
masks, distillation losses + program merge, NAS simulated-annealing
controller."""
from paddle_tpu.contrib.slim import distillation, nas, prune, quantization  # noqa: F401
