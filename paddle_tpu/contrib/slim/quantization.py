"""Quantization-aware training rewrite.

Reference: python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py (QuantizationTransformPass rewrites an IrGraph:
fake_quantize on inputs/weights of quantizable ops, fake_dequantize after)
and operators' fake_quantize_*_op.cc.

TPU note: int8 inference on TPU goes through XLA's native int8 matmul
path; QAT here simulates quantization in fp32 (identical math to the
reference's fake ops) so trained scales transfer.
"""
from __future__ import annotations

from typing import Optional, Set

from paddle_tpu import framework, unique_name
from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import one

__all__ = ["QuantizationTransformPass", "quantize_program"]


@register_op("fake_quantize_dequantize_abs_max")
def fake_quantize_dequantize_abs_max(inputs, attrs):
    """reference: operators/fake_quantize_op.cc — symmetric abs-max
    quantize+dequantize in one op (straight-through estimator under vjp:
    the rounding is piecewise-constant, so grads flow through the scale
    path; matches the reference's behavior)."""
    import jax
    import jax.numpy as jnp

    x = one(inputs, "X")
    bits = attrs.get("bit_length", 8)
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    q = jnp.round(x / scale * qmax)
    q = jnp.clip(q, -qmax, qmax)
    out = q * scale / qmax
    # straight-through: out = x + stop_grad(quantized - x)
    out = x + jax.lax.stop_gradient(out - x)
    return {"Out": out, "OutScale": scale.reshape(1)}


class QuantizationTransformPass:
    """reference: quantization_pass.py QuantizationTransformPass."""

    def __init__(self, quantizable_op_type=("conv2d", "depthwise_conv2d", "mul", "matmul"),
                 weight_bits: int = 8, activation_bits: int = 8):
        self.quantizable = set(quantizable_op_type)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits

    def apply(self, program) -> None:
        block = program.global_block()
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if op.type not in self.quantizable or op.attrs.get("op_role") == "backward":
                i += 1
                continue
            inserted = 0
            for slot, names in list(op.inputs.items()):
                new_names = []
                for n in names:
                    v = block._find_var_recursive(n)
                    if v is None or v.dtype not in ("float32",):
                        new_names.append(n)
                        continue
                    is_weight = isinstance(v, framework.Parameter)
                    bits = self.weight_bits if is_weight else self.activation_bits
                    qname = unique_name.generate(n + ".quantized")
                    sname = unique_name.generate(n + ".quant_scale")
                    block.create_var(name=qname, shape=v.shape, dtype="float32")
                    block.create_var(name=sname, shape=[1], dtype="float32", stop_gradient=True)
                    block._insert_op(
                        i + inserted,
                        type="fake_quantize_dequantize_abs_max",
                        inputs={"X": [n]},
                        outputs={"Out": [qname], "OutScale": [sname]},
                        attrs={"bit_length": bits, "op_role": op.attrs.get("op_role", "forward")},
                    )
                    inserted += 1
                    new_names.append(qname)
                op.inputs[slot] = new_names
            i += inserted + 1
        program.version += 1


def quantize_program(program, **kwargs):
    QuantizationTransformPass(**kwargs).apply(program)
    return program
