"""Quantization-aware training rewrite.

Reference: python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py (QuantizationTransformPass rewrites an IrGraph:
fake_quantize on inputs/weights of quantizable ops, fake_dequantize after)
and operators' fake_quantize_*_op.cc.

TPU note: int8 inference on TPU goes through XLA's native int8 matmul
path; QAT here simulates quantization in fp32 (identical math to the
reference's fake ops) so trained scales transfer.
"""
from __future__ import annotations

from typing import Optional, Set

from paddle_tpu import framework, unique_name
from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import one

__all__ = ["QuantizationTransformPass", "QuantizationFreezePass",
           "ConvertToInt8Pass", "AddQuantDequantPass",
           "ScaleForTrainingPass", "ScaleForInferencePass",
           "quantize_program", "freeze_program"]


@register_op("fake_quantize_dequantize_abs_max")
def fake_quantize_dequantize_abs_max(inputs, attrs):
    """reference: operators/fake_quantize_op.cc — symmetric abs-max
    quantize+dequantize in one op (straight-through estimator under vjp:
    the rounding is piecewise-constant, so grads flow through the scale
    path; matches the reference's behavior)."""
    import jax
    import jax.numpy as jnp

    x = one(inputs, "X")
    bits = attrs.get("bit_length", 8)
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    q = jnp.round(x / scale * qmax)
    q = jnp.clip(q, -qmax, qmax)
    out = q * scale / qmax
    # straight-through: out = x + stop_grad(quantized - x)
    out = x + jax.lax.stop_gradient(out - x)
    return {"Out": out, "OutScale": scale.reshape(1)}


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             no_grad_set={"InScale", "InState", "InAccum"})
def fake_quantize_dequantize_moving_average_abs_max(inputs, attrs):
    """reference: operators/fake_quantize_op.cc:513 + fake_quantize_op.h
    FindMovingAverageAbsMaxFunctor — activation quantization with a
    persisted moving-average scale:

    train:  state = rate*state + 1; accum = rate*accum + max|x|;
            scale = accum/state   (state/accum/scale write back to their
            persistable vars through the executor's state path)
    test:   scale = InScale (frozen; no state update)

    Quant-dequant is the same symmetric abs-max rounding as the abs_max
    op, straight-through under vjp."""
    import jax
    import jax.numpy as jnp

    x = one(inputs, "X")
    bits = attrs.get("bit_length", 8)
    qmax = float(2 ** (bits - 1) - 1)
    rate = float(attrs.get("moving_rate", 0.9))
    is_test = bool(attrs.get("is_test", False))
    if is_test:
        scale = one(inputs, "InScale").reshape(())
        scale = jnp.maximum(scale, 1e-8)
        extra = {}
    else:
        cur = jnp.max(jnp.abs(x))
        state = one(inputs, "InState").reshape(())
        accum = one(inputs, "InAccum").reshape(())
        state = rate * state + 1.0
        accum = rate * accum + cur
        scale = jnp.maximum(accum / state, 1e-8)
        extra = {"OutState": state.reshape(1), "OutAccum": accum.reshape(1)}
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax)
    out = q * scale / qmax
    out = x + jax.lax.stop_gradient(out - x)
    return {"Out": out, "OutScale": scale.reshape(1), **extra}


@register_op("fake_quantize_dequantize_range_abs_max",
             no_grad_set={"InScale", "InScales", "Iter"})
def fake_quantize_dequantize_range_abs_max(inputs, attrs):
    """reference: operators/fake_quantize_op.cc FakeQuantizeRangeAbsMax
    + FindRangeAbsMaxFunctor — activation scale = max over a sliding
    WINDOW of per-batch abs-max values (window_size slots, ring-buffer
    indexed by the step counter); test mode uses the stored InScale.
    Straight-through under vjp."""
    import jax
    import jax.numpy as jnp

    x = one(inputs, "X")
    bits = attrs.get("bit_length", 8)
    qmax = float(2 ** (bits - 1) - 1)
    if bool(attrs.get("is_test", False)):
        scale = jnp.maximum(one(inputs, "InScale").reshape(()), 1e-8)
        extra = {}
    else:
        window = one(inputs, "InScales")
        it = one(inputs, "Iter").reshape(()).astype(jnp.int32)
        cur = jnp.max(jnp.abs(x))
        idx = jnp.mod(it, window.shape[0])
        window = window.at[idx].set(cur)
        n_valid = jnp.minimum(it + 1, window.shape[0])
        valid = jnp.arange(window.shape[0]) < n_valid
        scale = jnp.maximum(jnp.max(jnp.where(valid, window, -jnp.inf)), 1e-8)
        extra = {"OutScales": window,
                 "IterOut": (it + 1).astype(jnp.int32).reshape(1)}
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax)
    out = x + jax.lax.stop_gradient(q * scale / qmax - x)
    return {"Out": out, "OutScale": scale.reshape(1), **extra}


@register_op("fake_channel_wise_quantize_dequantize_abs_max")
def fake_channel_wise_quantize_dequantize_abs_max(inputs, attrs):
    """reference: operators/fake_quantize_op.cc:521
    FakeChannelWiseQuantizeAbsMax (+ the pass's paired dequant) — one
    abs-max scale PER OUTPUT CHANNEL (dim 0: conv OIHW filters), which
    preserves accuracy for conv weights whose channels differ in range.
    Straight-through under vjp like the tensor-wise op."""
    import jax
    import jax.numpy as jnp

    x = one(inputs, "X")
    bits = attrs.get("bit_length", 8)
    qmax = float(2 ** (bits - 1) - 1)
    flat = x.reshape(x.shape[0], -1)
    scale = jnp.maximum(jnp.max(jnp.abs(flat), axis=1), 1e-8)  # [C]
    bshape = (x.shape[0],) + (1,) * (x.ndim - 1)
    s = scale.reshape(bshape)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    out = q * s / qmax
    out = x + jax.lax.stop_gradient(out - x)
    return {"Out": out, "OutScale": scale}


@register_op("dequantize_channel_wise_abs_max", differentiable=False)
def dequantize_channel_wise_abs_max(inputs, attrs):
    """reference: operators/fake_dequantize_op.cc
    FakeChannelWiseDequantizeMaxAbs — Out = X * Scale[c] / max_range,
    scale broadcast over dim 0 (int8 per-channel frozen weights)."""
    import jax.numpy as jnp

    x = one(inputs, "X")
    scale = one(inputs, "Scale")
    max_range = float(attrs.get("max_range", 127.0))
    bshape = (x.shape[0],) + (1,) * (x.ndim - 1)
    return {"Out": x.astype(jnp.float32) * (scale.reshape(bshape) / max_range)}


@register_op("dequantize_abs_max", differentiable=False)
def dequantize_abs_max(inputs, attrs):
    """reference: operators/fake_dequantize_op.cc fake_dequantize_max_abs
    — Out = Scale * X / max_range.  In a frozen program X is a real int8
    weight parameter; the product reproduces the QAT fake-quant values
    bit-for-bit (same scale, same rounding), so frozen inference matches
    the fake-quant program exactly."""
    import jax.numpy as jnp

    x = one(inputs, "X")
    scale = one(inputs, "Scale")
    max_range = float(attrs.get("max_range", 127.0))
    return {"Out": x.astype(jnp.float32) * (scale.reshape(()) / max_range)}


_MA_STATE_SPECS = (("scale", [1], 0.001, "float32"),
                   ("state", [1], 1.0, "float32"),
                   ("accum", [1], 1.0, "float32"))


def _create_ma_state_vars(block, startup_block, base_name,
                          specs=_MA_STATE_SPECS):
    """Create persistable quantizer-state vars + their startup
    fill_constant initializers; shared by the MA quantizers, the
    out-scale recorders, and the range-window quantizer.  ``specs``:
    (suffix, shape, init_value, dtype) tuples — defaults to the
    reference MA triple (scale 0.001, state 1, accum 1)."""
    names = {}
    for suffix, shape, init, dtype in specs:
        vn = unique_name.generate("%s.quant_%s" % (base_name, suffix))
        block.create_var(name=vn, shape=list(shape), dtype=dtype,
                         persistable=True, stop_gradient=True)
        if startup_block is not None:
            startup_block.create_var(name=vn, shape=list(shape), dtype=dtype,
                                     persistable=True, stop_gradient=True)
            startup_block.append_op(
                type="fill_constant", inputs={},
                outputs={"Out": [vn]},
                attrs={"shape": list(shape), "value": float(init),
                       "dtype": dtype},
            )
        names[suffix] = vn
    return names


class QuantizationFreezePass:
    """reference: slim/quantization/quantization_pass.py:541
    QuantizationFreezePass — fold trained fake-quant scales into REAL
    int8 weight tensors for inference.

    For every ``fake_quantize_dequantize_abs_max`` op whose input is a
    Parameter: quantize the trained fp32 weight to an int8 persistable
    (``<w>.int8``, 4x smaller on disk and in HBM), store its scale
    (``<w>.dequant_scale``), and replace the fake op with
    ``dequantize_abs_max`` feeding the consumer — XLA folds the dequant
    multiply into the consuming matmul/conv.  Activation handling
    depends on how QAT quantized them: ``abs_max`` (dynamic) ops are
    kept as-is — the per-batch scale IS the trained behavior — while
    ``moving_average_abs_max`` and ``range_abs_max`` ops get their
    trained persisted scale FIXED (``is_test=True``; no further state
    mutation), matching the reference freeze's recorded-scale
    semantics.  Frozen output
    therefore matches the fake-quant program exactly, and the program
    stays AnalysisPredictor-loadable.
    """

    def __init__(self, scope, place=None, weight_bits: int = 8):
        self._scope = scope
        self._place = place
        self._weight_bits = weight_bits

    def apply(self, program) -> None:
        import numpy as np

        block = program.global_block()
        frozen = 0
        # moving-average activation quantizers: fix the trained scale
        # (is_test) so inference uses the converged value and never
        # mutates state (reference freeze keeps the recorded scales)
        for op in block.ops:
            if op.type in ("fake_quantize_dequantize_moving_average_abs_max",
                           "fake_quantize_dequantize_range_abs_max"):
                op.attrs["is_test"] = True
                frozen += 1
        weight_fake_types = ("fake_quantize_dequantize_abs_max",
                             "fake_channel_wise_quantize_dequantize_abs_max")
        for i, op in enumerate(list(block.ops)):
            if op.type not in weight_fake_types:
                continue
            channel_wise = op.type.startswith("fake_channel_wise")
            xname = op.inputs["X"][0]
            var = block._find_var_recursive(xname)
            if not isinstance(var, framework.Parameter):
                continue  # activation quant stays dynamic (see docstring)
            # the bits the op actually trained with (stamped by
            # QuantizationTransformPass) — NOT this pass's default, or
            # non-8-bit QAT would silently re-quantize at the wrong
            # width and break the exact-parity contract
            bits = int(op.attrs.get("bit_length", self._weight_bits))
            if bits > 8:
                raise ValueError(
                    "freeze: weight %r trained with bit_length=%d; int8 "
                    "storage holds at most 8 bits" % (xname, bits)
                )
            qmax = float(2 ** (bits - 1) - 1)
            wv = self._scope.get(xname)
            if wv is None:
                raise RuntimeError(
                    "freeze: weight %r is not initialized in the scope — "
                    "train (or run startup) before freezing" % xname
                )
            w = np.asarray(wv)
            if channel_wise:
                flat = np.abs(w.reshape(w.shape[0], -1))
                scale = np.maximum(flat.max(axis=1), 1e-8)       # [C]
                s_b = scale.reshape((w.shape[0],) + (1,) * (w.ndim - 1))
                scale_arr = scale.astype(np.float32)
            else:
                scale = max(float(np.max(np.abs(w))), 1e-8)
                s_b = scale
                scale_arr = np.asarray([scale], np.float32)
            wq = np.clip(np.round(w / s_b * qmax), -qmax, qmax).astype(
                np.int8
            )
            qname = xname + ".int8"
            sname = xname + ".dequant_scale"
            block.create_var(
                name=qname, shape=list(w.shape), dtype="int8",
                persistable=True, stop_gradient=True,
            )
            block.create_var(
                name=sname, shape=[int(scale_arr.shape[0])], dtype="float32",
                persistable=True, stop_gradient=True,
            )
            self._scope.set(qname, wq)
            self._scope.set(sname, scale_arr)
            out_name = op.outputs["Out"][0]
            idx = block.ops.index(op)
            block._remove_op(idx)
            block._insert_op(
                idx,
                type=("dequantize_channel_wise_abs_max" if channel_wise
                      else "dequantize_abs_max"),
                inputs={"X": [qname], "Scale": [sname]},
                outputs={"Out": [out_name]},
                attrs={"max_range": qmax,
                       "op_role": op.attrs.get("op_role", "forward")},
            )
            frozen += 1
        if frozen == 0:
            raise ValueError(
                "freeze: no weight fake-quant ops found — apply "
                "QuantizationTransformPass (QAT) before freezing"
            )
        program.version += 1


def freeze_program(program, scope, place=None, weight_bits=8):
    """Convenience wrapper: freeze a QAT program in place and return it."""
    QuantizationFreezePass(scope, place, weight_bits).apply(program)
    return program


class ConvertToInt8Pass:
    """reference: quantization_pass.py:836 — convert quantized weights
    to real int8 storage.  On this build that conversion IS the freeze
    pass (int8 params + dequantize ops, 4x smaller on disk/HBM), so this
    class delegates to QuantizationFreezePass — kept as its own name for
    reference API parity."""

    def __init__(self, scope, place=None):
        self._scope = scope
        self._place = place

    def apply(self, program) -> None:
        block = program.global_block()
        # reference recipe is freeze-then-convert: an already-frozen
        # program (dequantize ops present, no weight fake ops left) is
        # already int8 — a no-op here, not an error
        has_dequant = any(op.type.startswith("dequantize_")
                          for op in block.ops)
        has_weight_fake = any(
            op.type in ("fake_quantize_dequantize_abs_max",
                        "fake_channel_wise_quantize_dequantize_abs_max")
            and isinstance(block._find_var_recursive(op.inputs["X"][0]),
                           framework.Parameter)
            for op in block.ops
        )
        if has_dequant and not has_weight_fake:
            return
        QuantizationFreezePass(self._scope, self._place).apply(program)


@register_op("moving_average_abs_max_scale",
             no_grad_set={"InScale", "InState", "InAccum"})
def moving_average_abs_max_scale(inputs, attrs):
    """reference: operators/fake_quantize_op.cc:528
    MovingAverageAbsMaxScale — identity forward that RECORDS a
    moving-average abs-max scale of its input (observability for int8
    engines; no quantization applied)."""
    import jax.numpy as jnp

    x = one(inputs, "X")
    if bool(attrs.get("is_test", False)):
        return {"Out": x, "OutScale": one(inputs, "InScale").reshape(1)}
    rate = float(attrs.get("moving_rate", 0.9))
    cur = jnp.max(jnp.abs(x))
    state = rate * one(inputs, "InState").reshape(()) + 1.0
    accum = rate * one(inputs, "InAccum").reshape(()) + cur
    scale = jnp.maximum(accum / state, 1e-8)
    return {"Out": x, "OutScale": scale.reshape(1),
            "OutState": state.reshape(1), "OutAccum": accum.reshape(1)}


class ScaleForTrainingPass:
    """reference: quantization_pass.py ScaleForTrainingPass — attach a
    moving_average_abs_max_scale recorder to every output of the listed
    op types, so inference engines get calibrated output thresholds."""

    def __init__(self, scope=None, place=None, moving_rate=0.9,
                 op_types=("conv2d", "depthwise_conv2d", "mul", "matmul")):
        self._moving_rate = moving_rate
        self._op_types = set(op_types)

    def apply(self, program, startup_program) -> None:
        block = program.global_block()
        sb = startup_program.global_block()
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if (op.type not in self._op_types
                    or op.attrs.get("op_role") == "backward"
                    or op.attrs.get("__out_scale__")):
                i += 1
                continue
            out_slot = "Output" if "Output" in op.outputs else "Out"
            out_name = op.outputs[out_slot][0]
            names = _create_ma_state_vars(block, sb, out_name + ".out")
            passthrough = unique_name.generate(out_name + ".scaled")
            v = block._find_var_recursive(out_name)
            block.create_var(name=passthrough, shape=v.shape, dtype=v.dtype)
            block._insert_op(
                i + 1,
                type="moving_average_abs_max_scale",
                inputs={"X": [out_name], "InScale": [names["scale"]],
                        "InState": [names["state"]],
                        "InAccum": [names["accum"]]},
                outputs={"Out": [passthrough], "OutScale": [names["scale"]],
                         "OutState": [names["state"]],
                         "OutAccum": [names["accum"]]},
                attrs={"moving_rate": self._moving_rate, "is_test": False},
            )
            op.attrs["__out_scale__"] = names["scale"]
            # rewire downstream readers onto the recorded output so the
            # op is live (identity, so numerics are unchanged)
            for later in block.ops[i + 2:]:
                for slot, ns in later.inputs.items():
                    later.inputs[slot] = [
                        passthrough if nm == out_name else nm for nm in ns
                    ]
            i += 2
        program.version += 1


class ScaleForInferencePass:
    """reference: quantization_pass.py ScaleForInferencePass — stamp the
    trained output thresholds onto the ops (``out_threshold`` attr) and
    freeze the recorders (is_test)."""

    def __init__(self, scope):
        self._scope = scope

    def apply(self, program) -> None:
        import numpy as np

        block = program.global_block()
        for op in block.ops:
            if op.type == "moving_average_abs_max_scale":
                op.attrs["is_test"] = True
        for op in block.ops:
            sname = op.attrs.get("__out_scale__")
            if sname:
                val = self._scope.get(sname)
                if val is not None:
                    op.attrs["out_threshold"] = float(np.asarray(val).reshape(-1)[0])
        program.version += 1


class AddQuantDequantPass:
    """reference: quantization_pass.py AddQuantDequantPass — quantize
    the inputs of ops OUTSIDE the matmul family (elementwise_add, pool,
    activations feeding concat...) with moving-average quantizers, so
    int8 engines see calibrated ranges on every edge."""

    _DEFAULT_OPS = ("elementwise_add", "pool2d")

    def __init__(self, scope=None, place=None, moving_rate=0.9,
                 quant_bits=8, quantizable_op_type=None):
        self._transform = QuantizationTransformPass(
            quantizable_op_type=tuple(quantizable_op_type or self._DEFAULT_OPS),
            weight_bits=quant_bits, activation_bits=quant_bits,
            activation_quantize_type="moving_average_abs_max",
            moving_rate=moving_rate,
            skip_weights=True,  # only activations (reference semantics)
        )

    def apply(self, program, startup_program) -> None:
        self._transform.apply(program, startup_program=startup_program)


class QuantizationTransformPass:
    """reference: quantization_pass.py QuantizationTransformPass.

    ``activation_quantize_type``:

    * ``"abs_max"`` (default) — dynamic per-batch activation scales,
      computed in-graph (nothing persisted).
    * ``"moving_average_abs_max"`` — the reference's trainable-scale
      mode: per-activation persistable scale/state/accum vars updated
      by the moving-average op each step (init scale 0.001, state and
      accum 1, matching _insert_quant_moving_average_abs_max_op); pass
      ``startup_program=`` to ``apply`` so the state vars get their
      initializers.  The freeze pass then fixes activation scales to
      the trained values (is_test).
    * ``"range_abs_max"`` — scale = max over a sliding ``window_size``
      window of per-batch abs-max values (persistable window + int32
      step counter); also needs ``startup_program=`` and is fixed at
      freeze like the moving-average mode.
    """

    def __init__(self, quantizable_op_type=("conv2d", "depthwise_conv2d", "mul", "matmul"),
                 weight_bits: int = 8, activation_bits: int = 8,
                 activation_quantize_type: str = "abs_max",
                 weight_quantize_type: str = "abs_max",
                 moving_rate: float = 0.9,
                 window_size: int = 10000,
                 skip_weights: bool = False):
        if activation_quantize_type not in (
                "abs_max", "moving_average_abs_max", "range_abs_max"):
            raise ValueError(
                "activation_quantize_type must be abs_max, "
                "moving_average_abs_max, or range_abs_max (got %r; the "
                "reference also forbids channel_wise for activations)"
                % activation_quantize_type
            )
        if weight_quantize_type not in ("abs_max", "channel_wise_abs_max"):
            raise ValueError(
                "weight_quantize_type must be abs_max or "
                "channel_wise_abs_max (got %r)" % weight_quantize_type
            )
        self.quantizable = set(quantizable_op_type)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.activation_quantize_type = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type
        self.moving_rate = moving_rate
        self.window_size = window_size
        # AddQuantDequantPass mode: quantize only ACTIVATION inputs —
        # a bias Parameter feeding elementwise_add must not be
        # fake-quantized (the reference pass skips persistables)
        self.skip_weights = skip_weights

    def _insert_moving_average(self, block, startup, i, n, v, bits):
        qname = unique_name.generate(n + ".quantized")
        block.create_var(name=qname, shape=v.shape, dtype="float32")
        sb = startup.global_block() if startup is not None else None
        names = _create_ma_state_vars(block, sb, n)
        block._insert_op(
            i,
            type="fake_quantize_dequantize_moving_average_abs_max",
            inputs={"X": [n], "InScale": [names["scale"]],
                    "InState": [names["state"]],
                    "InAccum": [names["accum"]]},
            outputs={"Out": [qname], "OutScale": [names["scale"]],
                     "OutState": [names["state"]],
                     "OutAccum": [names["accum"]]},
            attrs={"bit_length": bits, "moving_rate": self.moving_rate,
                   "is_test": False, "op_role": "forward"},
        )
        return qname

    def _insert_range(self, block, startup, i, n, v, bits):
        qname = unique_name.generate(n + ".quantized")
        block.create_var(name=qname, shape=v.shape, dtype="float32")
        # Iter is int32 like the reference's integer tensor — a float32
        # counter silently stops advancing at 2^24 steps, freezing the
        # ring buffer on one slot
        names = _create_ma_state_vars(
            block, startup.global_block(), n,
            specs=(("scale", [1], 0.001, "float32"),
                   ("scales", [self.window_size], 0.0, "float32"),
                   ("iter", [1], 0, "int32")),
        )
        block._insert_op(
            i,
            type="fake_quantize_dequantize_range_abs_max",
            inputs={"X": [n], "InScale": [names["scale"]],
                    "InScales": [names["scales"]], "Iter": [names["iter"]]},
            outputs={"Out": [qname], "OutScale": [names["scale"]],
                     "OutScales": [names["scales"]],
                     "IterOut": [names["iter"]]},
            attrs={"bit_length": bits, "window_size": self.window_size,
                   "is_test": False, "op_role": "forward"},
        )
        return qname

    def apply(self, program, startup_program=None) -> None:
        block = program.global_block()
        act_mode = self.activation_quantize_type
        use_ma = act_mode == "moving_average_abs_max"
        use_range = act_mode == "range_abs_max"
        if (use_ma or use_range) and startup_program is None:
            raise ValueError(
                "%s needs startup_program= so the scale-state vars get "
                "initializers" % act_mode
            )
        # one quantizer per VAR (reference: dequantized_vars cache) — an
        # activation feeding two quantizable ops shares one scale/state
        quantized: dict = {}
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if op.type not in self.quantizable or op.attrs.get("op_role") == "backward":
                i += 1
                continue
            inserted = 0
            for slot, names in list(op.inputs.items()):
                new_names = []
                for n in names:
                    v = block._find_var_recursive(n)
                    if v is None or v.dtype not in ("float32",):
                        new_names.append(n)
                        continue
                    if n in quantized:
                        new_names.append(quantized[n])
                        continue
                    is_weight = isinstance(v, framework.Parameter)
                    if is_weight and self.skip_weights:
                        new_names.append(n)
                        continue
                    bits = self.weight_bits if is_weight else self.activation_bits
                    # channel-wise only for CONV weights (the reference
                    # pass applies _insert_channel_quant_op to
                    # conv/depthwise weights; mul weights stay abs_max)
                    channel_wise = (
                        is_weight
                        and self.weight_quantize_type == "channel_wise_abs_max"
                        and op.type in ("conv2d", "depthwise_conv2d")
                    )
                    if not is_weight and use_ma:
                        qname = self._insert_moving_average(
                            block, startup_program, i + inserted, n, v, bits
                        )
                    elif not is_weight and use_range:
                        qname = self._insert_range(
                            block, startup_program, i + inserted, n, v, bits
                        )
                    else:
                        qname = unique_name.generate(n + ".quantized")
                        sname = unique_name.generate(n + ".quant_scale")
                        n_ch = int(v.shape[0]) if channel_wise else 1
                        block.create_var(name=qname, shape=v.shape, dtype="float32")
                        block.create_var(name=sname, shape=[n_ch],
                                         dtype="float32", stop_gradient=True)
                        block._insert_op(
                            i + inserted,
                            type=("fake_channel_wise_quantize_dequantize_abs_max"
                                  if channel_wise
                                  else "fake_quantize_dequantize_abs_max"),
                            inputs={"X": [n]},
                            outputs={"Out": [qname], "OutScale": [sname]},
                            attrs={"bit_length": bits, "op_role": op.attrs.get("op_role", "forward")},
                        )
                    inserted += 1
                    quantized[n] = qname
                    new_names.append(qname)
                op.inputs[slot] = new_names
            i += inserted + 1
        program.version += 1


def quantize_program(program, **kwargs):
    QuantizationTransformPass(**kwargs).apply(program)
    return program
