"""Quantization-aware training rewrite.

Reference: python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py (QuantizationTransformPass rewrites an IrGraph:
fake_quantize on inputs/weights of quantizable ops, fake_dequantize after)
and operators' fake_quantize_*_op.cc.

TPU note: int8 inference on TPU goes through XLA's native int8 matmul
path; QAT here simulates quantization in fp32 (identical math to the
reference's fake ops) so trained scales transfer.
"""
from __future__ import annotations

from typing import Optional, Set

from paddle_tpu import framework, unique_name
from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import one

__all__ = ["QuantizationTransformPass", "QuantizationFreezePass",
           "quantize_program", "freeze_program"]


@register_op("fake_quantize_dequantize_abs_max")
def fake_quantize_dequantize_abs_max(inputs, attrs):
    """reference: operators/fake_quantize_op.cc — symmetric abs-max
    quantize+dequantize in one op (straight-through estimator under vjp:
    the rounding is piecewise-constant, so grads flow through the scale
    path; matches the reference's behavior)."""
    import jax
    import jax.numpy as jnp

    x = one(inputs, "X")
    bits = attrs.get("bit_length", 8)
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    q = jnp.round(x / scale * qmax)
    q = jnp.clip(q, -qmax, qmax)
    out = q * scale / qmax
    # straight-through: out = x + stop_grad(quantized - x)
    out = x + jax.lax.stop_gradient(out - x)
    return {"Out": out, "OutScale": scale.reshape(1)}


@register_op("dequantize_abs_max", differentiable=False)
def dequantize_abs_max(inputs, attrs):
    """reference: operators/fake_dequantize_op.cc fake_dequantize_max_abs
    — Out = Scale * X / max_range.  In a frozen program X is a real int8
    weight parameter; the product reproduces the QAT fake-quant values
    bit-for-bit (same scale, same rounding), so frozen inference matches
    the fake-quant program exactly."""
    import jax.numpy as jnp

    x = one(inputs, "X")
    scale = one(inputs, "Scale")
    max_range = float(attrs.get("max_range", 127.0))
    return {"Out": x.astype(jnp.float32) * (scale.reshape(()) / max_range)}


class QuantizationFreezePass:
    """reference: slim/quantization/quantization_pass.py:541
    QuantizationFreezePass — fold trained fake-quant scales into REAL
    int8 weight tensors for inference.

    For every ``fake_quantize_dequantize_abs_max`` op whose input is a
    Parameter: quantize the trained fp32 weight to an int8 persistable
    (``<w>.int8``, 4x smaller on disk and in HBM), store its scale
    (``<w>.dequant_scale``), and replace the fake op with
    ``dequantize_abs_max`` feeding the consumer — XLA folds the dequant
    multiply into the consuming matmul/conv.  Activation fake-quant ops
    are kept as dynamic abs-max quant-dequant (this build's QAT computes
    activation scales in-graph rather than persisting a moving average,
    so freezing them would change semantics; the kept op IS the trained
    behavior).  Frozen output therefore matches the fake-quant program
    exactly, and the program stays AnalysisPredictor-loadable.
    """

    def __init__(self, scope, place=None, weight_bits: int = 8):
        self._scope = scope
        self._place = place
        self._weight_bits = weight_bits

    def apply(self, program) -> None:
        import numpy as np

        block = program.global_block()
        frozen = 0
        for i, op in enumerate(list(block.ops)):
            if op.type != "fake_quantize_dequantize_abs_max":
                continue
            xname = op.inputs["X"][0]
            var = block._find_var_recursive(xname)
            if not isinstance(var, framework.Parameter):
                continue  # activation quant stays dynamic (see docstring)
            # the bits the op actually trained with (stamped by
            # QuantizationTransformPass) — NOT this pass's default, or
            # non-8-bit QAT would silently re-quantize at the wrong
            # width and break the exact-parity contract
            bits = int(op.attrs.get("bit_length", self._weight_bits))
            if bits > 8:
                raise ValueError(
                    "freeze: weight %r trained with bit_length=%d; int8 "
                    "storage holds at most 8 bits" % (xname, bits)
                )
            qmax = float(2 ** (bits - 1) - 1)
            wv = self._scope.get(xname)
            if wv is None:
                raise RuntimeError(
                    "freeze: weight %r is not initialized in the scope — "
                    "train (or run startup) before freezing" % xname
                )
            w = np.asarray(wv)
            scale = max(float(np.max(np.abs(w))), 1e-8)
            wq = np.clip(np.round(w / scale * qmax), -qmax, qmax).astype(
                np.int8
            )
            qname = xname + ".int8"
            sname = xname + ".dequant_scale"
            block.create_var(
                name=qname, shape=list(w.shape), dtype="int8",
                persistable=True, stop_gradient=True,
            )
            block.create_var(
                name=sname, shape=[1], dtype="float32",
                persistable=True, stop_gradient=True,
            )
            self._scope.set(qname, wq)
            self._scope.set(sname, np.asarray([scale], np.float32))
            out_name = op.outputs["Out"][0]
            idx = block.ops.index(op)
            block._remove_op(idx)
            block._insert_op(
                idx,
                type="dequantize_abs_max",
                inputs={"X": [qname], "Scale": [sname]},
                outputs={"Out": [out_name]},
                attrs={"max_range": qmax,
                       "op_role": op.attrs.get("op_role", "forward")},
            )
            frozen += 1
        if frozen == 0:
            raise ValueError(
                "freeze: no weight fake-quant ops found — apply "
                "QuantizationTransformPass (QAT) before freezing"
            )
        program.version += 1


def freeze_program(program, scope, place=None, weight_bits=8):
    """Convenience wrapper: freeze a QAT program in place and return it."""
    QuantizationFreezePass(scope, place, weight_bits).apply(program)
    return program


class QuantizationTransformPass:
    """reference: quantization_pass.py QuantizationTransformPass."""

    def __init__(self, quantizable_op_type=("conv2d", "depthwise_conv2d", "mul", "matmul"),
                 weight_bits: int = 8, activation_bits: int = 8):
        self.quantizable = set(quantizable_op_type)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits

    def apply(self, program) -> None:
        block = program.global_block()
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if op.type not in self.quantizable or op.attrs.get("op_role") == "backward":
                i += 1
                continue
            inserted = 0
            for slot, names in list(op.inputs.items()):
                new_names = []
                for n in names:
                    v = block._find_var_recursive(n)
                    if v is None or v.dtype not in ("float32",):
                        new_names.append(n)
                        continue
                    is_weight = isinstance(v, framework.Parameter)
                    bits = self.weight_bits if is_weight else self.activation_bits
                    qname = unique_name.generate(n + ".quantized")
                    sname = unique_name.generate(n + ".quant_scale")
                    block.create_var(name=qname, shape=v.shape, dtype="float32")
                    block.create_var(name=sname, shape=[1], dtype="float32", stop_gradient=True)
                    block._insert_op(
                        i + inserted,
                        type="fake_quantize_dequantize_abs_max",
                        inputs={"X": [n]},
                        outputs={"Out": [qname], "OutScale": [sname]},
                        attrs={"bit_length": bits, "op_role": op.attrs.get("op_role", "forward")},
                    )
                    inserted += 1
                    new_names.append(qname)
                op.inputs[slot] = new_names
            i += inserted + 1
        program.version += 1


def quantize_program(program, **kwargs):
    QuantizationTransformPass(**kwargs).apply(program)
    return program
