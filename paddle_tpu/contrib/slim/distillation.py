"""Knowledge distillation losses (reference: python/paddle/fluid/contrib/
slim/distillation/distillation_strategy.py + distiller.py — FSP / L2 /
soft-label losses merged into the student program).

Here the losses are layer functions over (teacher_var, student_var)
pairs — compose them into the student's loss; the whole
teacher+student+loss graph compiles into one XLA module, so the teacher
forward rides the same step (the reference merges graphs the same way).
"""
from __future__ import annotations

__all__ = ["soft_label_loss", "l2_loss", "fsp_loss", "merge"]


def merge(teacher_program, student_program, data_name_map=None, place=None,
          scope=None, name_prefix="teacher_"):
    """Append the teacher program's ops into the student program with
    prefixed var names (reference: distillation merge).  Returns the
    mapping of teacher var -> merged var name."""
    from paddle_tpu import framework

    data_name_map = data_name_map or {}
    sblock = student_program.global_block()
    tblock = teacher_program.global_block()
    rename = {}
    for var in tblock.vars.values():
        if var.name in data_name_map:
            rename[var.name] = data_name_map[var.name]
            continue
        new_name = name_prefix + var.name
        rename[var.name] = new_name
        if not sblock.has_var(new_name):
            sblock.create_var(
                name=new_name, shape=var.shape, dtype=var.dtype,
                persistable=var.persistable, stop_gradient=True,
            )
    for op in tblock.ops:
        inputs = {s: [rename.get(n, n) for n in ns] for s, ns in op.inputs.items()}
        outputs = {s: [rename.get(n, n) for n in ns] for s, ns in op.outputs.items()}
        sblock.append_op(type=op.type, inputs=inputs, outputs=outputs, attrs=dict(op.attrs))
    student_program.version += 1
    if scope is not None:
        # copy already-initialized teacher values to their merged names
        # (run the teacher startup into this scope first)
        import jax.numpy as jnp

        for var in tblock.vars.values():
            if not var.persistable or var.name in data_name_map:
                continue
            val = scope.get(var.name)
            if val is not None:
                scope.set(rename[var.name], jnp.asarray(val))
    return rename


def soft_label_loss(teacher_logits, student_logits, teacher_temperature=1.0,
                    student_temperature=1.0):
    """KL(student || teacher-softened) soft-label loss (reference:
    distiller.py soft_label_loss)."""
    from paddle_tpu import layers

    t = layers.softmax(layers.scale(teacher_logits, scale=1.0 / teacher_temperature))
    s = layers.log_softmax(layers.scale(student_logits, scale=1.0 / student_temperature))
    return layers.mean(layers.reduce_sum(t * (-s), dim=-1))


def l2_loss(teacher_feature, student_feature):
    from paddle_tpu import layers

    diff = teacher_feature - student_feature
    return layers.mean(layers.reduce_sum(diff * diff, dim=-1))


def fsp_loss(teacher_a, teacher_b, student_a, student_b):
    """Flow-of-solution-procedure loss: L2 between gram matrices of two
    feature maps [N, C, H, W] (reference: distiller.py fsp_loss)."""
    from paddle_tpu import layers

    def gram(a, b):
        n, ca = a.shape[0], a.shape[1]
        cb = b.shape[1]
        hw = int(a.shape[2]) * int(a.shape[3])
        fa = layers.reshape(a, [0, ca, hw])
        fb = layers.reshape(b, [0, cb, hw])
        return layers.scale(
            layers.matmul(fa, layers.transpose(fb, [0, 2, 1])), scale=1.0 / hw
        )

    gt = gram(teacher_a, teacher_b)
    gs = gram(student_a, student_b)
    diff = gt - gs
    return layers.mean(diff * diff)
