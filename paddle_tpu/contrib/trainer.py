"""High-level Trainer/Inferencer API (reference: contrib/trainer.py +
contrib/inferencer.py — the book chapters' train(num_epochs,
event_handler, reader) loop with Begin/End Epoch/Step events and a
param_path handoff to the Inferencer)."""
from __future__ import annotations

import contextlib

from paddle_tpu import framework, io, unique_name
from paddle_tpu.executor import Executor
from paddle_tpu.framework import CPUPlace
from paddle_tpu.scope import Scope, scope_guard

__all__ = ["BeginEpochEvent", "EndEpochEvent", "BeginStepEvent",
           "EndStepEvent", "CheckpointConfig", "Trainer", "Inferencer"]


class BeginEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """reference: trainer.py:122 — periodic persistable saves."""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10):
        self.checkpoint_dir = checkpoint_dir
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(1, int(epoch_interval))
        self.step_interval = max(1, int(step_interval))


class Trainer:
    """reference: contrib/trainer.py:169.

    ``train_func`` builds the net and returns ``[loss]`` (or
    ``[loss, *metrics]``); ``optimizer_func`` returns the optimizer.
    ``train`` iterates ``reader()`` batches in ``feed_order``, firing
    the event objects above through ``event_handler``.
    """

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None):
        self.place = place or CPUPlace()
        self.scope = Scope()
        self._stopped = False
        self.checkpoint_cfg = checkpoint_config

        self._checkpoint_serial = 0
        self.startup_program = framework.Program()
        self.train_program = framework.Program()
        with framework.program_guard(self.train_program,
                                     self.startup_program):
            with unique_name.guard():
                outs = train_func()
                if not isinstance(outs, (list, tuple)):
                    outs = [outs]
                self.train_func_outputs = list(outs)
                self.loss = outs[0]
                optimizer = optimizer_func()
                optimizer.minimize(self.loss)
        self.test_program = self.train_program.clone(for_test=True)

        self.exe = Executor(self.place)
        with self._prog_and_scope_guard():
            self.exe.run(self.startup_program)
            if param_path:
                io.load_persistables(self.exe, param_path,
                                     main_program=self.train_program)

    @contextlib.contextmanager
    def _prog_and_scope_guard(self):
        with framework.program_guard(self.train_program,
                                     self.startup_program):
            with scope_guard(self.scope):
                yield

    def stop(self):
        """Break out of ``train`` after the current step."""
        self._stopped = True

    def _feed(self, feed_order, batch):
        if len(batch) != len(feed_order):
            raise ValueError(
                "feed_order has %d names but the reader batch has %d "
                "elements (%s)" % (len(feed_order), len(batch),
                                   list(feed_order))
            )
        return {name: data for name, data in zip(feed_order, batch)}

    def _save_checkpoint(self):
        """Numbered snapshots with rotation (reference: trainer.py
        _save_checkpoint + clean_checkpoint)."""
        import os
        import shutil

        cfg = self.checkpoint_cfg
        serial = self._checkpoint_serial
        self._checkpoint_serial += 1
        path = os.path.join(cfg.checkpoint_dir, "checkpoint_%d" % serial)
        io.save_persistables(self.exe, path, self.train_program)
        drop = serial - cfg.max_num_checkpoints
        if drop >= 0:
            stale = os.path.join(cfg.checkpoint_dir, "checkpoint_%d" % drop)
            shutil.rmtree(stale, ignore_errors=True)

    def train(self, num_epochs, event_handler, reader=None, feed_order=None):
        if reader is None or feed_order is None:
            raise ValueError("train needs reader= and feed_order=")
        self._stopped = False
        fetch = [v.name for v in self.train_func_outputs]
        with self._prog_and_scope_guard():
            step = 0
            for epoch_id in range(num_epochs):
                event_handler(BeginEpochEvent(epoch_id))
                for step_id, batch in enumerate(reader()):
                    if self._stopped:
                        return
                    begin = BeginStepEvent(epoch_id, step_id)
                    event_handler(begin)
                    metrics = self.exe.run(
                        self.train_program,
                        feed=self._feed(feed_order, batch),
                        fetch_list=fetch if begin.fetch_metrics else [],
                    )
                    event_handler(EndStepEvent(epoch_id, step_id, metrics))
                    step += 1
                    cfg = self.checkpoint_cfg
                    if (cfg and cfg.checkpoint_dir
                            and step % cfg.step_interval == 0):
                        self._save_checkpoint()
                event_handler(EndEpochEvent(epoch_id))
                cfg = self.checkpoint_cfg
                if (cfg and cfg.checkpoint_dir
                        and (epoch_id + 1) % cfg.epoch_interval == 0):
                    self._save_checkpoint()

    def test(self, reader, feed_order):
        """Mean of each train_func output over the reader (the
        reference's _test_by_executor path)."""
        import numpy as np

        fetch = [v.name for v in self.train_func_outputs]
        sums, count = None, 0
        with self._prog_and_scope_guard():
            for batch in reader():
                vals = self.exe.run(self.test_program,
                                    feed=self._feed(feed_order, batch),
                                    fetch_list=fetch)
                vals = [np.asarray(v).mean() for v in vals]
                sums = vals if sums is None else [a + b for a, b in
                                                  zip(sums, vals)]
                count += 1
        return [s / max(count, 1) for s in (sums or [])]

    def save_params(self, param_path):
        with self._prog_and_scope_guard():
            io.save_persistables(self.exe, param_path, self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes):
        with self._prog_and_scope_guard():
            io.save_inference_model(
                param_path, feeded_var_names,
                [self.train_func_outputs[i] for i in target_var_indexes],
                self.exe, self.test_program)


class Inferencer:
    """reference: contrib/inferencer.py:31 — rebuild the net via
    ``infer_func`` (returns the predict var), load params from
    ``param_path``, and ``infer({name: array})``."""

    def __init__(self, infer_func, param_path, place=None, parallel=False):
        self.place = place or CPUPlace()
        self.scope = Scope()
        self.inference_program = framework.Program()
        startup = framework.Program()
        with framework.program_guard(self.inference_program, startup):
            with unique_name.guard():
                self.predict_var = infer_func()
        self.exe = Executor(self.place)
        with scope_guard(self.scope):
            io.load_params(self.exe, param_path,
                           main_program=self.inference_program)
        self.inference_program = self.inference_program.clone(for_test=True)

    def infer(self, inputs, return_numpy=True):
        if not isinstance(inputs, dict):
            raise ValueError(
                "inputs should be a map of {'input_name': input_var}")
        with scope_guard(self.scope):
            return self.exe.run(self.inference_program, feed=inputs,
                                fetch_list=[self.predict_var.name],
                                return_numpy=return_numpy)
