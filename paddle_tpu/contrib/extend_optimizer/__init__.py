"""Decoupled weight decay wrapper (reference: contrib/extend_optimizer/
extend_optimizer_with_weight_decay.py — subtracts lr*coeff*param_prev
after the base optimizer's update, AdamW-style)."""
from __future__ import annotations

__all__ = ["extend_with_decoupled_weight_decay"]


def extend_with_decoupled_weight_decay(base_optimizer):
    """Returns a subclass of ``base_optimizer`` taking an extra
    ``weight_decay`` argument; the decay applies to the PRE-update param
    value, decoupled from the gradient (reference semantics)."""
    from paddle_tpu import framework
    from paddle_tpu.layer_helper import LayerHelper

    class OptimizerWithDecoupledWeightDecay(base_optimizer):
        def __init__(self, weight_decay=0.0, *args, **kwargs):
            super().__init__(*args, **kwargs)
            self._decoupled_weight_decay = float(weight_decay)

        def _append_optimize_op(self, block, param_and_grad):
            param = param_and_grad[0]
            coeff = self._decoupled_weight_decay
            if not coeff:
                return super()._append_optimize_op(block, param_and_grad)
            helper = LayerHelper("decoupled_wd")
            # snapshot the pre-update value
            snap = helper.create_variable_for_type_inference(param.dtype)
            block.append_op(type="assign", inputs={"X": [param.name]},
                            outputs={"Out": [snap.name]}, attrs={})
            op = super()._append_optimize_op(block, param_and_grad)
            # param -= lr * coeff * snapshot
            lr = self._create_param_lr(param)
            scaled = helper.create_variable_for_type_inference(param.dtype)
            block.append_op(
                type="elementwise_mul",
                inputs={"X": [snap.name], "Y": [lr.name]},
                outputs={"Out": [scaled.name]}, attrs={})
            dec = helper.create_variable_for_type_inference(param.dtype)
            block.append_op(
                type="scale", inputs={"X": [scaled.name]},
                outputs={"Out": [dec.name]}, attrs={"scale": coeff})
            block.append_op(
                type="elementwise_sub",
                inputs={"X": [param.name], "Y": [dec.name]},
                outputs={"Out": [param.name]},
                attrs={"op_role": "optimize"})
            return op

    OptimizerWithDecoupledWeightDecay.__name__ = (
        "DecoupledWeightDecay" + base_optimizer.__name__)
    return OptimizerWithDecoupledWeightDecay
