"""Legacy quantize transpiler surface (reference: contrib/quantize/
quantize_transpiler.py QuantizeTranspiler) — delegates to the slim QAT
rewrite (contrib/slim/quantization.py), which is the maintained path.

``calibrate_int8_program`` is the post-training-quantization entry the
mixed-precision SERVING path rides (``save_inference_model``'s
``precision_policy={"dtype": "int8", ...}``): no QAT required — the
slim transform pass inserts moving-average activation quantizers, a
handful of calibration feeds settle their scales through the normal
executor, and the freeze pass folds real int8 weights.  The result is
a frozen inference program + a scratch scope holding its (int8) state,
ready to save as a precision variant sub-model.
"""
from __future__ import annotations

__all__ = ["QuantizeTranspiler", "calibrate_int8_program"]


def calibrate_int8_program(program, executor, calibration_feeds,
                           fetch_names, base_scope=None,
                           weight_bits=8, activation_bits=8,
                           moving_rate=0.5):
    """Post-training int8 calibration of a PRUNED inference program.

    ``program`` is cloned (never mutated); ``calibration_feeds`` is a
    non-empty sequence of feed dicts run through the transformed
    program so the moving-average activation scales converge on real
    data (bench_calibration.py-style: representative batches, not the
    training set).  Weights are read from ``base_scope`` (default: the
    current global scope), COPIED into a scratch scope, and frozen to
    int8 there — the caller's fp32 state is untouched.

    ``moving_rate`` defaults to 0.5 (not QAT's 0.9): post-training
    calibration sees a handful of batches, and the faster decay lets
    the activation scales converge on real magnitudes instead of
    staying anchored to the 0.001 init — with 0.9, even 3 calibration
    batches leave scales ~4x under-estimated and the parity gate
    (rightly) refuses the export.

    Returns ``(frozen_program, scratch_scope)``.
    """
    from paddle_tpu import framework
    from paddle_tpu.contrib.slim.quantization import (
        QuantizationFreezePass,
        QuantizationTransformPass,
    )
    from paddle_tpu.scope import Scope, global_scope, scope_guard

    calibration_feeds = list(calibration_feeds or ())
    if not calibration_feeds:
        raise ValueError(
            "int8 calibration needs at least one calibration feed "
            "(a representative batch per entry)")
    base_scope = base_scope if base_scope is not None else global_scope()
    work = program.clone()
    startup = framework.Program()
    QuantizationTransformPass(
        weight_bits=weight_bits, activation_bits=activation_bits,
        activation_quantize_type="moving_average_abs_max",
        moving_rate=moving_rate,
    ).apply(work, startup_program=startup)
    scratch = Scope()
    for v in work.list_vars():
        if not v.persistable or v.is_data:
            continue
        val = base_scope.get(v.name)
        if val is not None:
            scratch.set(v.name, val)
    with scope_guard(scratch):
        executor.run(startup)
        for feed in calibration_feeds:
            executor.run(work, feed=feed, fetch_list=list(fetch_names))
        QuantizationFreezePass(
            scratch, weight_bits=weight_bits).apply(work)
    return work, scratch


class QuantizeTranspiler:
    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type="abs_max",
                 weight_quantize_type="abs_max", window_size=10000):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.activation_quantize_type = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type

    def training_transpile(self, program=None, startup_program=None):
        from paddle_tpu import framework
        from paddle_tpu.contrib.slim.quantization import (
            QuantizationTransformPass,
        )

        program = program or framework.default_main_program()
        QuantizationTransformPass(
            weight_bits=self.weight_bits,
            activation_bits=self.activation_bits,
            activation_quantize_type=self.activation_quantize_type,
            weight_quantize_type=self.weight_quantize_type,
        ).apply(program, startup_program=startup_program)
        return program

    def freeze_program(self, program, place=None, scope=None):
        """Fold trained fake-quant scales into real int8 weights
        (reference: quantize_transpiler.py freeze_program →
        slim QuantizationFreezePass, quantization_pass.py:541)."""
        from paddle_tpu.contrib.slim.quantization import (
            QuantizationFreezePass,
        )
        from paddle_tpu.scope import global_scope

        scope = scope or global_scope()
        QuantizationFreezePass(
            scope, place, weight_bits=self.weight_bits
        ).apply(program)
        return program
