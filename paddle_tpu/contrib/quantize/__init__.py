"""Legacy quantize transpiler surface (reference: contrib/quantize/
quantize_transpiler.py QuantizeTranspiler) — delegates to the slim QAT
rewrite (contrib/slim/quantization.py), which is the maintained path."""
from __future__ import annotations

__all__ = ["QuantizeTranspiler"]


class QuantizeTranspiler:
    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type="abs_max",
                 weight_quantize_type="abs_max", window_size=10000):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.activation_quantize_type = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type

    def training_transpile(self, program=None, startup_program=None):
        from paddle_tpu import framework
        from paddle_tpu.contrib.slim.quantization import (
            QuantizationTransformPass,
        )

        program = program or framework.default_main_program()
        QuantizationTransformPass(
            weight_bits=self.weight_bits,
            activation_bits=self.activation_bits,
            activation_quantize_type=self.activation_quantize_type,
            weight_quantize_type=self.weight_quantize_type,
        ).apply(program, startup_program=startup_program)
        return program

    def freeze_program(self, program, place=None, scope=None):
        """Fold trained fake-quant scales into real int8 weights
        (reference: quantize_transpiler.py freeze_program →
        slim QuantizationFreezePass, quantization_pass.py:541)."""
        from paddle_tpu.contrib.slim.quantization import (
            QuantizationFreezePass,
        )
        from paddle_tpu.scope import global_scope

        scope = scope or global_scope()
        QuantizationFreezePass(
            scope, place, weight_bits=self.weight_bits
        ).apply(program)
        return program
