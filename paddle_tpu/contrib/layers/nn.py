"""contrib layer fns (reference: contrib/layers/nn.py)."""
from __future__ import annotations

__all__ = ["fused_elemwise_activation"]


def fused_elemwise_activation(x, y, functor_list, axis=-1, scale=0.0,
                              save_intermediate_out=True):
    """reference: contrib/layers/nn.py fused_elemwise_activation — the
    fused CUDA kernel is an XLA-fusion no-op here: compose the named
    functors (e.g. ['elementwise_add', 'scale']) and let the compiler
    fuse them into one kernel."""
    from paddle_tpu.layers import tensor as ltensor

    supported = {"elementwise_add", "elementwise_sub", "elementwise_mul",
                 "scale", "relu", "tanh", "sigmoid"}
    unknown = [f for f in functor_list if f not in supported]
    if unknown:
        raise NotImplementedError(
            "fused_elemwise_activation functors %s (supported: %s)"
            % (unknown, sorted(supported)))
    out = None
    for f in functor_list:
        if f.startswith("elementwise_"):
            a = out if out is not None else x
            out = getattr(ltensor, f)(a, y, axis=axis)
        elif f == "scale":
            a = out if out is not None else x
            out = ltensor.scale(a, scale=scale)
        else:
            from paddle_tpu.layers import nn as lnn

            a = out if out is not None else x
            out = getattr(lnn, f)(a)
    return out
