"""contrib layers (reference: contrib/layers/nn.py + metric_op.py)."""
from paddle_tpu.contrib.layers.nn import fused_elemwise_activation  # noqa: F401

__all__ = ["fused_elemwise_activation"]
