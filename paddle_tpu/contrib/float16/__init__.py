"""Low-precision inference transpiler (reference: paddle/contrib/float16/
float16_transpiler.py Float16Transpiler).

TPU-native: the low-precision type is **bfloat16** (same exponent range
as fp32 — no loss-scale machinery needed, and the MXU computes in bf16
natively).  ``Float16Transpiler.transpile`` casts an inference program's
weights in the scope to bf16 and marks the program so feeds cast down
and fetches cast back up — users keep feeding/fetching fp32 like the
reference describes.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Float16Transpiler", "Bfloat16Transpiler"]

# params that keep full precision (normalization statistics/affine — the
# same keep-fp32 set as contrib.mixed_precision)
_KEEP_FP32_SUBSTR = ("_mean", "_variance", "batch_norm", "_bn_")


class Float16Transpiler:
    def transpile(self, program, place=None, scope=None):
        """Cast the program's parameters (in ``scope``) to bfloat16 and
        rewrite the program's parameter dtypes; feed vars stay fp32 (the
        executor casts feeds to each var's dtype on entry, and fetched
        values convert via np.asarray).  Returns the set of cast params."""
        import jax.numpy as jnp

        from paddle_tpu.scope import global_scope

        scope = scope or global_scope()
        cast = set()
        for p in program.all_parameters():
            if any(k in p.name for k in _KEEP_FP32_SUBSTR):
                continue
            val = scope.get(p.name)
            if val is None:
                raise RuntimeError(
                    "param %r not in scope — run startup / load first" % p.name)
            if not np.issubdtype(np.asarray(val).dtype, np.floating):
                continue
            scope.set(p.name, jnp.asarray(val, jnp.bfloat16))
            p.dtype = "bfloat16"
            cast.add(p.name)
        program.version += 1
        return cast


Bfloat16Transpiler = Float16Transpiler
