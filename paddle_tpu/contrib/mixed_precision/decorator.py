"""AMP decorator + program rewrite (reference: contrib/mixed_precision/
decorator.py:27,194; fp16_lists.py; fp16_utils.py rewrite_program)."""
from __future__ import annotations

import contextlib
from typing import Optional, Set

from paddle_tpu import framework, unique_name
from paddle_tpu.framework import Operator

__all__ = [
    "AutoMixedPrecisionLists",
    "OptimizerWithMixedPrecision",
    "decorate",
    "rewrite_program",
    "bf16_guard",
]


class AutoMixedPrecisionLists:
    """reference: fp16_lists.py — white (run low precision), black (keep
    fp32), gray (follow inputs).

    Gray ops matter for TPU throughput: ResNet-style models are
    HBM-bandwidth-bound, so the conv→BN→relu→add chains must keep their
    activation traffic in bf16 end to end.  Casting back to fp32 at every
    non-white op (the naive rewrite) doubles intermediate traffic and cost
    ~20% step time on the v5e bench.  Gray ops run in bf16 whenever any
    float input is already bf16; numerically sensitive internals (BN
    statistics) are computed in fp32 *inside* the kernel (ops/nn_ops.py
    batch_norm) where XLA fuses the casts for free."""

    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list: Set[str] = {
            "matmul", "mul", "conv2d", "depthwise_conv2d", "conv2d_transpose",
            "fused_attention",
        }
        self.black_list: Set[str] = {
            "softmax_with_cross_entropy", "cross_entropy", "mean", "sum",
            "reduce_mean", "reduce_sum", "softmax",
        }
        self.gray_list: Set[str] = {
            "batch_norm", "layer_norm", "group_norm",
            "relu", "relu6", "leaky_relu", "prelu", "elu", "gelu", "tanh",
            "sigmoid", "hard_sigmoid", "hard_swish", "swish", "brelu",
            "softplus", "softsign",
            "elementwise_add", "elementwise_sub", "elementwise_mul",
            "elementwise_div", "elementwise_max", "elementwise_min",
            "pool2d", "dropout", "pad", "pad2d",
            "reshape", "reshape2", "transpose", "transpose2", "squeeze",
            "squeeze2", "unsqueeze", "unsqueeze2", "flatten", "flatten2",
            "concat", "split", "slice", "stack", "scale", "expand",
            "gather", "lookup_table",
        }
        if custom_white_list:
            self.white_list |= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
            self.white_list -= set(custom_black_list)
            self.gray_list -= set(custom_black_list)


# Per-op input slots / output slots that must stay fp32 even when the op
# runs bf16 (running statistics, affine params — the BN kernel computes in
# fp32 internally and casts Y back to X's dtype).
_KEEP_FP32_IN = {
    "batch_norm": {"Scale", "Bias", "Mean", "Variance"},
    "layer_norm": {"Scale", "Bias"},
    "group_norm": {"Scale", "Bias"},
}
_KEEP_FP32_OUT = {
    "batch_norm": {"MeanOut", "VarianceOut", "SavedMean", "SavedVariance"},
    "layer_norm": {"Mean", "Variance"},
    "group_norm": {"Mean", "Variance"},
}


_LOW = "bfloat16"


def _cast_in(block, op_index, op: Operator, dtype: str, skip_slots=()) -> int:
    """Insert casts so ``op``'s float inputs arrive as ``dtype``; returns
    how many ops were inserted before ``op``."""
    inserted = 0
    for slot, names in list(op.inputs.items()):
        if slot in skip_slots:
            continue
        new_names = []
        for n in names:
            v = block._find_var_recursive(n)
            if v is None or v.dtype not in ("float32", "float64"):
                new_names.append(n)
                continue
            cast_name = unique_name.generate(n + ".cast_" + dtype)
            block.create_var(name=cast_name, shape=v.shape, dtype=dtype, stop_gradient=v.stop_gradient)
            block._insert_op(
                op_index + inserted,
                type="cast",
                inputs={"X": [n]},
                outputs={"Out": [cast_name]},
                attrs={"in_dtype": v.dtype, "out_dtype": dtype, "op_role": op.attrs.get("op_role", "forward")},
            )
            inserted += 1
            new_names.append(cast_name)
        op.inputs[slot] = new_names
    return inserted


def rewrite_program(main_program, amp_lists: Optional[AutoMixedPrecisionLists] = None):
    """Cast white-list ops to bf16 (reference: fp16_utils.py
    rewrite_program).  Outputs of white ops become bf16; black-list ops
    get their inputs cast back to fp32 lazily via a second pass."""
    amp_lists = amp_lists or AutoMixedPrecisionLists()
    block = main_program.global_block()

    i = 0
    low_vars: Set[str] = set()
    while i < len(block.ops):
        op = block.ops[i]
        def _flip_outputs_low(op, keep_out=()):
            for slot, names in op.outputs.items():
                if slot in keep_out:
                    continue
                for n in names:
                    v = block._find_var_recursive(n)
                    if v is not None and v.dtype == "float32":
                        v.dtype = _LOW
                        low_vars.add(n)

        if op.type in amp_lists.white_list:
            i += _cast_in(block, i, op, _LOW)
            _flip_outputs_low(op)
        elif op.type in amp_lists.gray_list:
            # follow inputs: stay bf16 if anything upstream already is —
            # keeps activation chains (conv→BN→relu→add) in bf16 so HBM
            # traffic halves; fp32-sensitive slots are exempted per op.
            has_low = any(
                n in low_vars for names in op.inputs.values() for n in names
            )
            if has_low:
                i += _cast_in(block, i, op, _LOW, skip_slots=_KEEP_FP32_IN.get(op.type, ()))
                _flip_outputs_low(op, keep_out=_KEEP_FP32_OUT.get(op.type, ()))
        else:
            # black list and everything unknown: cast bf16 inputs back up
            # inputs that became bf16 upstream get cast back to fp32
            inserted = 0
            for slot, names in list(op.inputs.items()):
                new_names = []
                for n in names:
                    if n in low_vars:
                        v = block._find_var_recursive(n)
                        cast_name = unique_name.generate(n + ".cast_fp32")
                        block.create_var(name=cast_name, shape=v.shape, dtype="float32", stop_gradient=v.stop_gradient)
                        block._insert_op(
                            i + inserted,
                            type="cast",
                            inputs={"X": [n]},
                            outputs={"Out": [cast_name]},
                            attrs={"in_dtype": _LOW, "out_dtype": "float32",
                                   "op_role": op.attrs.get("op_role", "forward")},
                        )
                        inserted += 1
                        new_names.append(cast_name)
                    else:
                        new_names.append(n)
                op.inputs[slot] = new_names
            i += inserted
        i += 1
    main_program.version += 1


@contextlib.contextmanager
def bf16_guard():
    """Parity with the reference's fp16_guard (ops built inside are
    eligible for low precision) — the rewrite is list-driven here, so this
    is a documentation no-op."""
    yield


class OptimizerWithMixedPrecision:
    """reference: decorator.py:27.  bf16 needs no loss scaling (same
    exponent range as fp32); the scaling fields exist for API parity and
    are honored when ``use_dynamic_loss_scaling`` is explicitly set."""

    def __init__(self, optimizer, amp_lists, init_loss_scaling=1.0,
                 use_dynamic_loss_scaling=False):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._loss_scaling = float(init_loss_scaling)
        self._use_dynamic_loss_scaling = use_dynamic_loss_scaling

    def get_loss_scaling(self):
        return self._loss_scaling

    def backward(self, loss, startup_program=None, parameter_list=None, no_grad_set=None, callbacks=None):
        from paddle_tpu import layers

        rewrite_program(loss.block.program, self._amp_lists)
        scaled = loss
        if self._loss_scaling != 1.0:
            scaled = layers.scale(loss, scale=self._loss_scaling)
        params_grads = self._optimizer.backward(
            scaled, startup_program, parameter_list, no_grad_set, callbacks
        )
        if self._loss_scaling != 1.0:
            from paddle_tpu.layers import tensor as ltensor

            unscaled = []
            for p, g in params_grads:
                if g is None:
                    unscaled.append((p, g))
                    continue
                gv = g if isinstance(g, framework.Variable) else loss.block.var(g)
                unscaled.append((p, ltensor.scale(gv, scale=1.0 / self._loss_scaling)))
            params_grads = unscaled
        return params_grads

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list, no_grad_set)
        ops = self._optimizer.apply_gradients(params_grads)
        return ops, params_grads


def decorate(optimizer, amp_lists=None, init_loss_scaling=1.0,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8, use_dynamic_loss_scaling=False):
    """reference: decorator.py:194."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling
    )
