"""Inference-side mixed precision: low-precision predictor VARIANTS.

The training-side rewrite (``decorator.rewrite_program``) has pointed
the white/black/gray lists at train programs since the seed; this
module points the same machinery at the *serving* path.  A PRUNED
inference program becomes a bf16 variant in three passes:

1. ``rewrite_program`` — the proven white/gray/black cast insertion
   (white ops run bf16, gray chains follow their inputs so conv→BN→
   relu→add activation traffic stays bf16 end to end, black ops get
   fp32 cast-ups);
2. ``hoist_param_casts`` — every inserted fp32→bf16 cast whose source
   is a persistable parameter is DELETED and the parameter itself is
   flipped to bf16: the dtype policy is applied at param-placement
   time (the variant scope holds a bf16 copy resident in HBM), not
   per dispatch — halving the weight bytes a serving step moves is
   the point, and a per-run cast would read the fp32 bytes anyway
   (SNIPPETS [2], fmengine's ``dtype_specs`` at shard/gather time,
   is the shape of this move);
3. ``cast_fetches_fp32`` — fetch targets keep their fp32 dtype and
   names (one cast op per bf16 fetch), so clients, the wire codec,
   and the parity gate never see bf16 leave the predictor.

The int8 variant rides the ``contrib/quantize`` seam instead (see
``paddle_tpu.contrib.quantize.calibrate_int8_program``): calibration
feeds settle moving-average activation scales, the freeze pass folds
real int8 weights, and the frozen program is saved as a sub-model the
loader reconstructs.

``max_rel_err`` is the parity gate's metric: exporting a precision
policy runs the variant against the fp32 program on parity feeds and
refuses (typed ``PrecisionParityError``) when the measured error
exceeds the policy's rtol — the bound then rides the manifest as the
endpoint's advertised accuracy contract.
"""
from __future__ import annotations

from collections import Counter
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from paddle_tpu.contrib.mixed_precision.decorator import (
    AutoMixedPrecisionLists,
    rewrite_program,
)
from paddle_tpu.core.types import PRECISION_ALIASES as _DTYPE_ALIASES

__all__ = [
    "PrecisionPolicyError",
    "PrecisionParityError",
    "DEFAULT_RTOL",
    "normalize_dtype",
    "build_bf16_variant",
    "hoist_param_casts",
    "cast_fetches_fp32",
    "cast_counts",
    "variant_scope",
    "max_rel_err",
    "synthetic_parity_feeds",
]


class PrecisionPolicyError(ValueError):
    """A malformed or unsupported precision policy (bad dtype, missing
    calibration data, composition with an incompatible feature)."""


class PrecisionParityError(PrecisionPolicyError):
    """The low-precision variant disagreed with fp32 beyond the
    policy's rtol at export — the parity gate refuses to ship it."""


#: default parity bounds per variant dtype (relative error on the
#: fetch outputs; bf16 carries ~2-3 significant digits — eps ~8e-3 —
#: so a few percent of accumulated error is the honest expectation;
#: int8 is calibration-dependent and looser)
DEFAULT_RTOL = {"bf16": 5e-2, "int8": 0.35}

def normalize_dtype(dtype: str) -> str:
    d = _DTYPE_ALIASES.get(str(dtype).lower())
    if d is None:
        raise PrecisionPolicyError(
            "unsupported precision dtype %r (supported: %s)"
            % (dtype, sorted(set(_DTYPE_ALIASES.values()))))
    return d


# ---------------------------------------------------------------------------
# bf16 variant passes
# ---------------------------------------------------------------------------
def hoist_param_casts(program) -> Set[str]:
    """Delete every fp32→bf16 cast of a persistable parameter and flip
    the parameter itself to bf16; returns the flipped names.

    Only parameters whose EVERY use goes through such a cast are
    hoisted — a parameter that also feeds an op expecting fp32 (a
    keep-fp32 slot, a black op) keeps its per-run cast, so hoisting can
    never change numerics, only WHERE the cast happens (load time vs
    every dispatch)."""
    block = program.global_block()
    uses: Counter = Counter()
    for op in block.ops:
        for names in op.inputs.values():
            uses.update(names)
    casts = []  # (op, src, out)
    for op in block.ops:
        if op.type != "cast" or op.attrs.get("out_dtype") != "bfloat16":
            continue
        src = op.inputs["X"][0]
        v = block._find_var_recursive(src)
        if (v is None or not v.persistable or v.is_data
                or v.dtype != "float32"):
            continue
        casts.append((op, src, op.outputs["Out"][0]))
    cast_uses = Counter(src for _, src, _ in casts)
    eligible = {src for src, n in cast_uses.items() if n == uses[src]}
    if not eligible:
        return set()
    rename: Dict[str, str] = {}
    drop = set()
    for op, src, out in casts:
        if src in eligible:
            rename[out] = src
            drop.add(id(op))
    block.ops = [op for op in block.ops if id(op) not in drop]
    for op in block.ops:
        for slot, names in op.inputs.items():
            op.inputs[slot] = [rename.get(n, n) for n in names]
    for out, src in rename.items():
        block.vars.pop(out, None)
    for src in eligible:
        block._find_var_recursive(src).dtype = "bfloat16"
    program.version += 1
    return eligible


def cast_fetches_fp32(program, fetch_names: Sequence[str]) -> int:
    """Pin every fetch target back to fp32 (same name, one appended
    cast op per bf16 fetch) so outputs keep the dtype the manifest and
    the wire codec advertise; returns the number of casts added."""
    block = program.global_block()
    n = 0
    for name in fetch_names:
        v = block._find_var_recursive(name)
        if v is None or v.dtype != "bfloat16":
            continue
        raw = name + ".bf16_raw"
        block.create_var(name=raw, shape=v.shape, dtype="bfloat16",
                         stop_gradient=v.stop_gradient)
        for op in block.ops:
            for slot, names in op.outputs.items():
                op.outputs[slot] = [raw if x == name else x for x in names]
            for slot, names in op.inputs.items():
                op.inputs[slot] = [raw if x == name else x for x in names]
        v.dtype = "float32"
        block.append_op(
            type="cast",
            inputs={"X": [raw]},
            outputs={"Out": [name]},
            attrs={"in_dtype": "bfloat16", "out_dtype": "float32",
                   "op_role": "forward"},
        )
        n += 1
    if n:
        program.version += 1
    return n


def cast_counts(program) -> Dict[str, int]:
    """Cast-op census of a rewritten program: ``to_low`` (fp32→bf16)
    and ``to_fp32`` (bf16→fp32 bounce/fetch casts).  Tests assert on
    these to pin "gray chains stay bf16 end to end"."""
    out = {"to_low": 0, "to_fp32": 0}
    for op in program.global_block().ops:
        if op.type != "cast":
            continue
        if op.attrs.get("out_dtype") in ("bfloat16", "float16"):
            out["to_low"] += 1
        else:
            out["to_fp32"] += 1
    return out


def build_bf16_variant(program, fetch_names: Sequence[str],
                       custom_white_list=None, custom_black_list=None
                       ) -> Tuple[object, Dict[str, object]]:
    """Clone ``program`` (a pruned inference program) into its bf16
    variant: rewrite → hoist param casts → pin fetches fp32.  Returns
    ``(variant_program, info)`` with ``info['cast_params']`` naming the
    parameters the variant stores as bf16 (the variant scope must hold
    bf16 copies for exactly these)."""
    variant = program.clone()
    lists = AutoMixedPrecisionLists(custom_white_list, custom_black_list)
    rewrite_program(variant, lists)
    cast_params = hoist_param_casts(variant)
    n_fetch_casts = cast_fetches_fp32(variant, fetch_names)
    info = {
        "cast_params": sorted(cast_params),
        "fetch_casts": n_fetch_casts,
        "cast_ops": cast_counts(variant),
    }
    return variant, info


def variant_scope(program, base_scope, cast_params: Set[str],
                  host_cast: bool = False):
    """A scope for the variant program sharing the base scope's values,
    with the hoisted parameters cast to bf16 ONCE (device-resident in
    bf16 from here on — this is the load-time "param placement" where
    the dtype policy lands).  Values not named ``cast_params`` are
    shared by reference (jax arrays are immutable).

    ``host_cast=True`` (the precision × sharding composed mode): the
    cast lands in HOST memory (numpy bf16 via ``ml_dtypes``) instead of
    on device, so the value stays a staged host array until the sharded
    dispatcher ``device_put``s it shard-by-shard — a bf16 tp/fsdp
    program then never materializes an fp32 (or full-width bf16) copy
    of a cast param on device."""
    import jax.numpy as jnp
    import ml_dtypes

    from paddle_tpu.scope import Scope

    sc = Scope()
    for v in program.list_vars():
        if not v.persistable or v.is_data:
            continue
        val = base_scope.get(v.name)
        if val is None:
            continue
        if v.name in cast_params:
            if host_cast:
                val = np.asarray(val).astype(ml_dtypes.bfloat16)
            else:
                val = jnp.asarray(val, jnp.bfloat16)
        sc.set(v.name, val)
    return sc


# ---------------------------------------------------------------------------
# parity gate helpers
# ---------------------------------------------------------------------------
def max_rel_err(ref_outs: Sequence, outs: Sequence) -> float:
    """Worst SCALE-relative error across fetch outputs: per array,
    ``max|a - b| / max(max|a|, 1e-6)`` in fp64.  Relative to the
    array's magnitude, not per element — raw-logit outputs legitimately
    cross zero, and a per-element denominator would report an infinite
    "error" on an element that rounds through it while every value is
    within bf16 rounding of the array's scale."""
    worst = 0.0
    for a, b in zip(ref_outs, outs):
        a = np.asarray(a, np.float64)
        b = np.asarray(b, np.float64)
        if a.shape != b.shape:
            raise PrecisionParityError(
                "variant output shape %s != fp32 output shape %s"
                % (b.shape, a.shape))
        if not a.size:
            continue
        scale = max(float(np.max(np.abs(a))), 1e-6)
        worst = max(worst, float(np.max(np.abs(a - b))) / scale)
    return worst


def synthetic_parity_feeds(program, feed_names: Sequence[str],
                           batch: int = 4, n_feeds: int = 2,
                           seed: int = 0) -> List[Dict[str, np.ndarray]]:
    """Deterministic parity feeds derived from the program's data vars:
    floats uniform in [-1, 1), integer feeds zeros (always in range for
    id/embedding inputs).  Callers with real calibration data should
    pass their own ``parity_feeds`` instead."""
    from paddle_tpu.core import types as core_types

    block = program.global_block()
    rng = np.random.RandomState(seed)
    feeds = []
    for _ in range(max(1, n_feeds)):
        feed = {}
        for name in feed_names:
            var = block.var(name)
            shape = (batch,) + tuple(
                1 if int(d) < 0 else int(d) for d in (var.shape or ())[1:])
            dt = core_types.np_dtype(var.dtype)
            if np.issubdtype(dt, np.floating):
                feed[name] = rng.uniform(-1, 1, shape).astype(dt)
            else:
                feed[name] = np.zeros(shape, dt)
        feeds.append(feed)
    return feeds
