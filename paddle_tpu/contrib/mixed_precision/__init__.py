"""Automatic mixed precision.

Reference: python/paddle/fluid/contrib/mixed_precision/ — decorate()
(decorator.py:194) wraps the optimizer, rewrite_program casts whitelisted
ops to fp16 with fp32 master weights and dynamic loss scaling.

TPU-native: the low-precision type is **bfloat16** — same exponent range
as fp32, so loss scaling is unnecessary (kept as API surface, default
off).  The rewrite casts inputs of MXU ops (matmul/conv family, the white
list) to bf16; XLA keeps the fused epilogues in higher precision and the
parameter/optimizer state stays fp32 (master weights by construction —
the cast is part of the graph, grads flow back through it to fp32).
"""
from paddle_tpu.contrib.mixed_precision.decorator import (  # noqa: F401
    AutoMixedPrecisionLists,
    OptimizerWithMixedPrecision,
    bf16_guard,
    decorate,
    rewrite_program,
)
