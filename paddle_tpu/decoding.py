"""Auto-regressive decoding: greedy + beam search.

Reference: paddle/fluid/operators/beam_search_op.cc +
beam_search_decode_op.cc, driven from Python by a While loop over
LoDTensorArray (layers/control_flow.py + book test
test_machine_translation.py).  The reference's per-step op dispatch with
ragged LoD beams becomes ONE compiled `lax.fori_loop`: beams are a dense
[batch, beam] axis, the whole decode loop (including the model forward)
lives in a single XLA module — no host round-trips between steps.

Two regimes:

* ``beam_search``/``greedy_search`` — generic: the model forward is
  re-run over the full padded prefix each step (any ``logits_fn``,
  O(T^2) forwards).
* ``beam_search_cached``/``greedy_search_cached`` — KV-cached: the
  caller provides ``step_fn(cache, tokens, t) -> (logits, cache)`` that
  consumes ONE token per step and carries per-layer key/value caches in
  the scan state (O(T) per step; the beam reorder gathers cache rows by
  parent).  ``make_transformer_lm_step_fn`` builds such a step from a
  trained ``models.transformer.transformer_lm`` Program's weights —
  exact parity with the full-prefix decode
  (tests/test_seq2seq_decode.py::test_cached_decode_*).
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

__all__ = [
    "beam_search", "greedy_search", "make_program_logits_fn",
    "beam_search_cached", "greedy_search_cached",
    "make_transformer_lm_step_fn",
    "make_transformer_lm_pooled_step_fn", "make_slot_decode_fns",
    "random_transformer_lm_state",
]


def random_transformer_lm_state(rng, vocab, d_model, n_layer, n_head,
                                d_inner, max_pos, name="lm"):
    """A randomly initialized transformer-LM weight dict with exactly
    the keys the ``make_transformer_lm_*_step_fn`` builders read —
    the one place the key/shape schema lives for benches and tests."""
    w = {name + "_word_emb": rng.randn(vocab, d_model) * 0.1,
         name + "_pos_emb": rng.randn(max_pos, d_model) * 0.1,
         name + "_head_w": rng.randn(d_model, vocab) * 0.1,
         name + "_head_b": np.zeros(vocab)}
    for i in range(n_layer):
        p = "%s_dec_%d" % (name, i)
        for nm, shp in (("_att_q", (d_model, d_model)),
                        ("_att_k", (d_model, d_model)),
                        ("_att_v", (d_model, d_model)),
                        ("_att_out", (d_model, d_model)),
                        ("_ffn_fc0", (d_model, d_inner)),
                        ("_ffn_fc1", (d_inner, d_model))):
            w[p + nm + "_w"] = rng.randn(*shp) * 0.1
            w[p + nm + "_b"] = np.zeros(shp[1])
        for ln in ("_ln1", "_ln2"):
            w[p + ln + "_scale"] = np.ones(d_model)
            w[p + ln + "_bias"] = np.zeros(d_model)
    return {k: np.asarray(v, "float32") for k, v in w.items()}


def make_program_logits_fn(program, state, feed_names, logits_name):
    """Lower an inference program into ``logits_fn(feeds_dict) -> logits``
    for use inside the decode loop.  ``state``: persistable name->array
    (trained params)."""
    from paddle_tpu.core import lowering

    block = program.global_block()
    fn = lowering.lower_block(block, feed_names, [logits_name], [])

    def logits_fn(feeds):
        fetches, _ = fn(dict(state), feeds)
        return fetches[0]

    return logits_fn


def _beam_core(step, state0, B, K, bos_id, eos_id, max_len, length_penalty):
    """Shared beam bookkeeping for the full-prefix and KV-cached paths.

    ``step(state, tokens_flat [B*K, max_len], t) -> (logits [B*K, V],
    state)`` returns the next-token logits for loop position ``t``
    (i.e. conditioned on the prefix through ``t - 1``); ``state`` is an
    arbitrary pytree (None for stateless full-prefix, per-layer KV
    caches for the cached path) whose leaves carry a leading B*K axis —
    after each selection its rows are gathered by the winning parents.
    """
    import jax
    import jax.numpy as jnp

    NEG = -1e9
    tokens0 = jnp.full((B, K, max_len), eos_id, dtype="int32")
    tokens0 = tokens0.at[:, :, 0].set(bos_id)
    scores0 = jnp.where(jnp.arange(K)[None, :] == 0, 0.0, NEG) * jnp.ones((B, 1))
    finished0 = jnp.zeros((B, K), dtype=bool)

    def body(t, carry):
        tokens, scores, finished, st = carry
        flat = tokens.reshape(B * K, max_len)
        logits, st = step(st, flat, t)
        logp = jax.nn.log_softmax(logits, axis=-1).reshape(B, K, -1)
        V = logp.shape[-1]
        # finished beams may only extend with EOS at zero cost
        eos_only = jnp.full((V,), NEG).at[eos_id].set(0.0)
        logp = jnp.where(finished[..., None], eos_only[None, None, :], logp)
        total = scores[..., None] + logp  # [B, K, V]
        top_scores, top_idx = jax.lax.top_k(total.reshape(B, K * V), K)
        parent = top_idx // V  # [B, K]
        tok = (top_idx % V).astype("int32")
        rows = (jnp.arange(B)[:, None] * K + parent).reshape(-1)
        tokens = jnp.take_along_axis(tokens, parent[..., None], axis=1)
        tokens = tokens.at[:, :, t].set(tok)
        finished = jnp.take_along_axis(finished, parent, axis=1) | (tok == eos_id)
        st = jax.tree.map(lambda c: c[rows], st)
        return tokens, top_scores, finished, st

    tokens, scores, finished, _ = jax.lax.fori_loop(
        1, max_len, body, (tokens0, scores0, finished0, state0)
    )
    if length_penalty > 0.0:
        lengths = jnp.sum((tokens != eos_id).astype("float32"), axis=-1) + 1.0
        scores = scores / (lengths ** length_penalty)
        order = jnp.argsort(-scores, axis=-1)
        tokens = jnp.take_along_axis(tokens, order[..., None], axis=1)
        scores = jnp.take_along_axis(scores, order, axis=1)
    return tokens, scores


def beam_search(
    logits_fn: Callable,
    src: np.ndarray,
    bos_id: int,
    eos_id: int,
    beam_size: int = 4,
    max_len: int = 16,
    src_feed_name: str = "src",
    tgt_feed_name: str = "tgt",
    length_penalty: float = 0.0,
    extra_feeds: Optional[dict] = None,
):
    """Returns (tokens [B, beam, max_len], scores [B, beam]) sorted best
    first.  ``logits_fn`` maps {src, tgt [N, max_len]} -> [N, max_len, V].
    """
    import jax.numpy as jnp

    src = jnp.asarray(src)
    B, K = src.shape[0], beam_size
    src_tiled = jnp.repeat(src, K, axis=0)  # [B*K, S]
    extra_tiled = {
        k: jnp.repeat(jnp.asarray(v), K, axis=0) for k, v in (extra_feeds or {}).items()
    }

    def step(state, flat, t):
        feeds = {src_feed_name: src_tiled, tgt_feed_name: flat}
        feeds.update(extra_tiled)
        logits = logits_fn(feeds)  # [B*K, T, V]
        return logits[:, t - 1, :], state

    return _beam_core(step, None, B, K, bos_id, eos_id, max_len, length_penalty)


def greedy_search(logits_fn, src, bos_id, eos_id, max_len=16, **kwargs):
    """Greedy = beam 1; returns (tokens [B, max_len], scores [B])."""
    tokens, scores = beam_search(
        logits_fn, src, bos_id, eos_id, beam_size=1, max_len=max_len, **kwargs
    )
    return tokens[:, 0], scores[:, 0]


# ---------------------------------------------------------------------------
# KV-cached decoding
# ---------------------------------------------------------------------------
def beam_search_cached(
    step_fn: Callable,
    init_cache,
    batch: int,
    bos_id: int,
    eos_id: int,
    beam_size: int = 4,
    max_len: int = 16,
    length_penalty: float = 0.0,
):
    """Beam search with a KV cache carried through the compiled loop.

    ``step_fn(cache, tokens [N] int32, t) -> (logits [N, V], cache)``:
    consume the token at position ``t`` and return logits for position
    ``t + 1``; cache leaves carry a leading ``N = batch * beam`` axis
    so the beam reorder can gather rows by parent.  ``init_cache``: the
    zeroed cache pytree (leaves ``[N, ...]``).  One lax.fori_loop, no
    host round-trips; each step is O(prefix) instead of the
    full-prefix re-run's O(prefix^2)."""

    def step(cache, flat, t):
        return step_fn(cache, flat[:, t - 1], t - 1)

    return _beam_core(step, init_cache, batch, beam_size, bos_id, eos_id,
                      max_len, length_penalty)


def greedy_search_cached(step_fn, init_cache, batch, bos_id, eos_id,
                         max_len=16, **kwargs):
    """Greedy = beam 1 on the cached path; returns ([B, max_len], [B])."""
    tokens, scores = beam_search_cached(
        step_fn, init_cache, batch, bos_id, eos_id, beam_size=1,
        max_len=max_len, **kwargs
    )
    return tokens[:, 0], scores[:, 0]


def make_transformer_lm_step_fn(
    state,
    vocab_size: int,
    d_model: int,
    n_layer: int,
    n_head: int,
    d_inner: int,
    max_len: int,
    name: str = "lm",
):
    """Build (step_fn, make_cache) for KV-cached decoding from a trained
    ``models.transformer.transformer_lm`` Program's weights.

    ``state``: persistable name -> array (the same dict
    ``make_program_logits_fn`` takes).  Mirrors the Program math exactly
    — post-LN blocks (eps 1e-5), exact (non-tanh) gelu FFN, per-head
    scaled dot product — on an incrementally updated ``[N, H, T, Dh]``
    key/value cache per layer, so cached decode == full-prefix decode
    bit-for-tolerance (parity-tested).

    Returns ``(step_fn, make_cache)`` where ``make_cache(n_rows)``
    allocates the zeroed cache for ``n_rows = batch * beam`` lanes.
    """
    import jax
    import jax.numpy as jnp

    d_head = d_model // n_head
    W = {k: jnp.asarray(v) for k, v in state.items()}

    def make_cache(n_rows: int):
        return [
            {
                "k": jnp.zeros((n_rows, n_head, max_len, d_head), "float32"),
                "v": jnp.zeros((n_rows, n_head, max_len, d_head), "float32"),
            }
            for _ in range(n_layer)
        ]

    scale = 1.0 / float(np.sqrt(d_head))

    def step_fn(cache, tokens, t):
        # tokens [N] int32; t: position being consumed
        x = W[name + "_word_emb"][tokens] + W[name + "_pos_emb"][t]
        return _lm_forward_one(W, name, cache, x, t, None, n_layer,
                               n_head, d_head, d_model, scale)

    return step_fn, make_cache


def _lm_forward_one(W, name, cache, x, t, ts, n_layer, n_head, d_head,
                    d_model, scale):
    """One incremental transformer-LM forward shared by the scalar-``t``
    and slot-pooled (per-row ``ts``) step fns.  Exactly one of ``t``
    (scalar loop position, all rows aligned) / ``ts`` ([N] int32, each
    row at its own position) is not None; the cache T axis is read from
    the cache itself so one builder serves every length rung."""
    import jax
    import jax.numpy as jnp

    T = cache[0]["k"].shape[2]
    n = x.shape[0]
    if ts is None:
        pos_ok = (jnp.arange(T) <= t)[None, None, :]       # [1,1,T]
        row_t = None
    else:
        pos_ok = (jnp.arange(T)[None, :] <= ts[:, None])[:, None, :]  # [N,1,T]
        row_t = (jnp.arange(T)[None, :] == ts[:, None])    # [N,T]
    new_cache = []
    for i in range(n_layer):
        p = "%s_dec_%d" % (name, i)
        q = _fc(W, x, p + "_att_q").reshape(n, n_head, d_head)
        k = _fc(W, x, p + "_att_k").reshape(n, n_head, d_head)
        v = _fc(W, x, p + "_att_v").reshape(n, n_head, d_head)
        if ts is None:
            kc = jax.lax.dynamic_update_index_in_dim(
                cache[i]["k"], k, t, axis=2)
            vc = jax.lax.dynamic_update_index_in_dim(
                cache[i]["v"], v, t, axis=2)
        else:
            # per-row scatter: each lane writes its OWN position — the
            # one-hot select is O(cache) like the attention itself
            sel = row_t[:, None, :, None]                  # [N,1,T,1]
            kc = jnp.where(sel, k[:, :, None, :], cache[i]["k"])
            vc = jnp.where(sel, v[:, :, None, :], cache[i]["v"])
        new_cache.append({"k": kc, "v": vc})
        scores = jnp.einsum("nhd,nhtd->nht", q, kc) * scale
        scores = jnp.where(pos_ok, scores, -1e9)
        w = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("nht,nhtd->nhd", w, vc).reshape(n, d_model)
        att = _fc(W, ctx, p + "_att_out")
        x = _ln(W, x + att, p + "_ln1")
        h = jax.nn.gelu(_fc(W, x, p + "_ffn_fc0"), approximate=False)
        x = _ln(W, x + _fc(W, h, p + "_ffn_fc1"), p + "_ln2")
    logits = _fc(W, x, name + "_head")
    return logits, new_cache


def _fc(W, x, pname):
    return x @ W[pname + "_w"] + W[pname + "_b"]


def _ln(W, x, pname):
    import jax.numpy as jnp

    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mean) / jnp.sqrt(var + 1e-5)
    return y * W[pname + "_scale"] + W[pname + "_bias"]


def make_transformer_lm_pooled_step_fn(
    state,
    vocab_size: int,
    d_model: int,
    n_layer: int,
    n_head: int,
    d_inner: int,
    name: str = "lm",
):
    """The slot-pool variant of :func:`make_transformer_lm_step_fn`.

    Continuous batching decodes a POOL of sequences that are each at a
    DIFFERENT position (a request admitted mid-flight starts its prefill
    while its neighbors are deep into generation), so the step consumes
    per-row positions: ``step_fn(cache, tokens [N] int32, ts [N] int32)
    -> (logits [N, V], cache)`` where row ``i`` consumes ``tokens[i]``
    at position ``ts[i]`` (cache row ``i`` updated at ``ts[i]``; its
    attention masked to positions ``<= ts[i]``).

    The cache T axis is read from the cache arrays themselves, so one
    step fn serves every length rung of the slot pool's bucket ladder:
    ``make_cache(n_rows, seq_len)`` allocates the zeroed pytree for one
    (slot-rung, length-rung) pair.  Math is identical to the scalar-t
    builder — with all rows at the same position the two are exactly
    equal (parity-tested in tests/test_seq2seq_decode.py).

    The pool relies on a write-before-read invariant instead of cache
    zeroing on slot reuse: a sequence at position ``ts`` has itself
    written every cache position ``<= ts`` (prefill consumes each prompt
    token through the same step), and the mask hides ``> ts`` — stale
    rows from a previous occupant are never read.
    """
    import jax.numpy as jnp

    d_head = d_model // n_head
    W = {k: jnp.asarray(v) for k, v in state.items()}
    scale = 1.0 / float(np.sqrt(d_head))

    def make_cache(n_rows: int, seq_len: int):
        return [
            {
                "k": jnp.zeros((n_rows, n_head, seq_len, d_head), "float32"),
                "v": jnp.zeros((n_rows, n_head, seq_len, d_head), "float32"),
            }
            for _ in range(n_layer)
        ]

    def step_fn(cache, tokens, ts):
        x = W[name + "_word_emb"][tokens] + W[name + "_pos_emb"][ts]
        return _lm_forward_one(W, name, cache, x, None, ts, n_layer,
                               n_head, d_head, d_model, scale)

    return step_fn, make_cache


# ---------------------------------------------------------------------------
# Slot-pool decode: the fused multi-token chunk + admit executables
# ---------------------------------------------------------------------------
def make_slot_decode_fns(step_fn, eos_id: int, steps: int):
    """Build the three pure functions the serving slot pool compiles per
    (slot-rung, length-rung) pair: ``chunk(state) -> state`` advancing
    every active slot by up to ``steps`` tokens in ONE device dispatch
    (a ``fori_loop`` — multi-step dispatch amortizes host overhead
    between scheduler interventions), ``admit(state, slot_mask,
    prompt, prompt_len, total_len) -> state`` seating one request into a
    free slot, and ``release(state, slot_mask) -> state`` deactivating
    slots mid-flight (deadline abort) so their lanes stop advancing.

    The pool state is a dict pytree (every leaf's axis 0 is the slot):

    * ``cache``    — the step fn's KV pytree (T axis read by the step)
    * ``tokens``   — [S, T] int32, position-indexed token buffer
    * ``pos``      — [S] int32, tokens consumed so far (the step eats
      index ``pos`` and produces the token for ``pos + 1``)
    * ``prompt_len``/``total_len`` — [S] int32 per-slot prompt size and
      overall length cap (prompt + generated <= total_len <= T)
    * ``active``/``finished`` — [S] bool scheduler flags
    * ``n_gen``    — [S] int32 generated-token count (prefill/decode
      ratio accounting reads the deltas host-side)

    Prefill and decode are the SAME step: while ``pos + 1 <
    prompt_len`` the produced token is discarded in favor of the stored
    prompt token (teacher forcing), so a freshly admitted prompt fills
    its cache inside the running batch — no separate prefill executable,
    no second compiled shape.  A slot finishes when it emits ``eos_id``
    or reaches ``total_len``; inactive slots are fully masked (their
    ``pos`` does not advance) and cost only the wasted lane math the
    bucket ladder already prices in.
    """
    import jax
    import jax.numpy as jnp

    def _body(_, state):
        tokens = state["tokens"]
        pos = state["pos"]
        active = state["active"]
        S, T = tokens.shape
        rows = jnp.arange(S)
        tok_in = tokens[rows, jnp.minimum(pos, T - 1)]
        logits, cache = step_fn(state["cache"], tok_in, pos)
        nxt = jnp.argmax(logits, axis=-1).astype("int32")
        write_idx = jnp.minimum(pos + 1, T - 1)
        in_prefill = (pos + 1) < state["prompt_len"]
        do_write = active & ~in_prefill
        cur = tokens[rows, write_idx]
        tokens = tokens.at[rows, write_idx].set(
            jnp.where(do_write, nxt, cur))
        newly_fin = do_write & (
            (nxt == eos_id) | ((pos + 2) >= state["total_len"]))
        return {
            "cache": cache,
            "tokens": tokens,
            "pos": jnp.where(active, pos + 1, pos),
            "prompt_len": state["prompt_len"],
            "total_len": state["total_len"],
            "active": active & ~newly_fin,
            "finished": state["finished"] | newly_fin,
            "n_gen": state["n_gen"] + do_write.astype("int32"),
        }

    def chunk(state):
        return jax.lax.fori_loop(0, steps, _body, state)

    def admit(state, slot_mask, prompt, prompt_len, total_len):
        # slot_mask [S] bool (one admitted slot), prompt [T] int32
        # (padded host-side), prompt_len/total_len () int32 scalars.
        # The cache passes through UNTOUCHED: the write-before-read
        # invariant (see make_transformer_lm_pooled_step_fn) makes
        # zeroing a reused slot's rows unnecessary.
        mask = slot_mask
        return {
            "cache": state["cache"],
            "tokens": jnp.where(mask[:, None], prompt[None, :],
                                state["tokens"]),
            "pos": jnp.where(mask, 0, state["pos"]),
            "prompt_len": jnp.where(mask, prompt_len, state["prompt_len"]),
            "total_len": jnp.where(mask, total_len, state["total_len"]),
            "active": state["active"] | mask,
            "finished": state["finished"] & ~mask,
            "n_gen": jnp.where(mask, 0, state["n_gen"]),
        }

    def release(state, slot_mask):
        # deactivate without finishing: the slot becomes seatable again
        # (its request was aborted host-side); tokens/cache stay — the
        # write-before-read invariant protects the next occupant
        return {
            "cache": state["cache"],
            "tokens": state["tokens"],
            "pos": state["pos"],
            "prompt_len": state["prompt_len"],
            "total_len": state["total_len"],
            "active": state["active"] & ~slot_mask,
            "finished": state["finished"] & ~slot_mask,
            "n_gen": state["n_gen"],
        }

    return chunk, admit, release
