"""Auto-regressive decoding: greedy + beam search.

Reference: paddle/fluid/operators/beam_search_op.cc +
beam_search_decode_op.cc, driven from Python by a While loop over
LoDTensorArray (layers/control_flow.py + book test
test_machine_translation.py).  The reference's per-step op dispatch with
ragged LoD beams becomes ONE compiled `lax.fori_loop`: beams are a dense
[batch, beam] axis, the whole decode loop (including the model forward)
lives in a single XLA module — no host round-trips between steps.

The model forward is re-run over the full padded prefix each step (no KV
cache yet — correctness-first; the compiled loop is still MXU-batched).
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

__all__ = ["beam_search", "greedy_search", "make_program_logits_fn"]


def make_program_logits_fn(program, state, feed_names, logits_name):
    """Lower an inference program into ``logits_fn(feeds_dict) -> logits``
    for use inside the decode loop.  ``state``: persistable name->array
    (trained params)."""
    from paddle_tpu.core import lowering

    block = program.global_block()
    fn = lowering.lower_block(block, feed_names, [logits_name], [])

    def logits_fn(feeds):
        fetches, _ = fn(dict(state), feeds)
        return fetches[0]

    return logits_fn


def beam_search(
    logits_fn: Callable,
    src: np.ndarray,
    bos_id: int,
    eos_id: int,
    beam_size: int = 4,
    max_len: int = 16,
    src_feed_name: str = "src",
    tgt_feed_name: str = "tgt",
    length_penalty: float = 0.0,
    extra_feeds: Optional[dict] = None,
):
    """Returns (tokens [B, beam, max_len], scores [B, beam]) sorted best
    first.  ``logits_fn`` maps {src, tgt [N, max_len]} -> [N, max_len, V].
    """
    import jax
    import jax.numpy as jnp

    src = jnp.asarray(src)
    B = src.shape[0]
    K = beam_size
    NEG = -1e9

    src_tiled = jnp.repeat(src, K, axis=0)  # [B*K, S]
    extra_tiled = {
        k: jnp.repeat(jnp.asarray(v), K, axis=0) for k, v in (extra_feeds or {}).items()
    }

    tokens0 = jnp.full((B, K, max_len), eos_id, dtype="int32")
    tokens0 = tokens0.at[:, :, 0].set(bos_id)
    scores0 = jnp.where(jnp.arange(K)[None, :] == 0, 0.0, NEG) * jnp.ones((B, 1))
    finished0 = jnp.zeros((B, K), dtype=bool)

    def body(t, carry):
        tokens, scores, finished = carry
        flat = tokens.reshape(B * K, max_len)
        feeds = {src_feed_name: src_tiled, tgt_feed_name: flat}
        feeds.update(extra_tiled)
        logits = logits_fn(feeds)  # [B*K, T, V]
        logp = jax.nn.log_softmax(logits[:, t - 1, :], axis=-1).reshape(B, K, -1)
        V = logp.shape[-1]
        # finished beams may only extend with EOS at zero cost
        eos_only = jnp.full((V,), NEG).at[eos_id].set(0.0)
        logp = jnp.where(finished[..., None], eos_only[None, None, :], logp)
        total = scores[..., None] + logp  # [B, K, V]
        top_scores, top_idx = jax.lax.top_k(total.reshape(B, K * V), K)
        parent = top_idx // V  # [B, K]
        tok = (top_idx % V).astype("int32")
        tokens = jnp.take_along_axis(tokens, parent[..., None], axis=1)
        tokens = tokens.at[:, :, t].set(tok)
        finished = jnp.take_along_axis(finished, parent, axis=1) | (tok == eos_id)
        return tokens, top_scores, finished

    tokens, scores, finished = jax.lax.fori_loop(
        1, max_len, body, (tokens0, scores0, finished0)
    )
    if length_penalty > 0.0:
        lengths = jnp.sum((tokens != eos_id).astype("float32"), axis=-1) + 1.0
        scores = scores / (lengths ** length_penalty)
        order = jnp.argsort(-scores, axis=-1)
        tokens = jnp.take_along_axis(tokens, order[..., None], axis=1)
        scores = jnp.take_along_axis(scores, order, axis=1)
    return tokens, scores


def greedy_search(logits_fn, src, bos_id, eos_id, max_len=16, **kwargs):
    """Greedy = beam 1; returns (tokens [B, max_len], scores [B])."""
    tokens, scores = beam_search(
        logits_fn, src, bos_id, eos_id, beam_size=1, max_len=max_len, **kwargs
    )
    return tokens[:, 0], scores[:, 0]
