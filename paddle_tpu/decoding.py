"""Auto-regressive decoding: greedy + beam search.

Reference: paddle/fluid/operators/beam_search_op.cc +
beam_search_decode_op.cc, driven from Python by a While loop over
LoDTensorArray (layers/control_flow.py + book test
test_machine_translation.py).  The reference's per-step op dispatch with
ragged LoD beams becomes ONE compiled `lax.fori_loop`: beams are a dense
[batch, beam] axis, the whole decode loop (including the model forward)
lives in a single XLA module — no host round-trips between steps.

Two regimes:

* ``beam_search``/``greedy_search`` — generic: the model forward is
  re-run over the full padded prefix each step (any ``logits_fn``,
  O(T^2) forwards).
* ``beam_search_cached``/``greedy_search_cached`` — KV-cached: the
  caller provides ``step_fn(cache, tokens, t) -> (logits, cache)`` that
  consumes ONE token per step and carries per-layer key/value caches in
  the scan state (O(T) per step; the beam reorder gathers cache rows by
  parent).  ``make_transformer_lm_step_fn`` builds such a step from a
  trained ``models.transformer.transformer_lm`` Program's weights —
  exact parity with the full-prefix decode
  (tests/test_seq2seq_decode.py::test_cached_decode_*).
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

__all__ = [
    "beam_search", "greedy_search", "make_program_logits_fn",
    "beam_search_cached", "greedy_search_cached",
    "make_transformer_lm_step_fn",
    "make_transformer_lm_pooled_step_fn", "make_slot_decode_fns",
    "make_transformer_lm_pooled_verify_fn", "make_prefix_admit_fn",
    "kv_leaf_seq_axis", "normalize_kv_dtype",
    "random_transformer_lm_state",
]

#: KV-cache storage dtypes the pooled builders accept.  "int8" stores
#: K/V rows quantized (per-slot-per-head-per-position absmax scales as
#: sibling ``k_scale``/``v_scale`` leaves — see paddle_tpu.quant),
#: quantize-on-write / dequant-at-attend inside the jitted step.
KV_DTYPES = ("fp32", "int8")


def normalize_kv_dtype(kv_dtype) -> str:
    d = str(kv_dtype or "fp32").lower()
    d = {"float32": "fp32", "fp32": "fp32", "int8": "int8"}.get(d)
    if d is None:
        raise ValueError(
            "unsupported kv_dtype %r (supported: %s)"
            % (kv_dtype, list(KV_DTYPES)))
    return d


def random_transformer_lm_state(rng, vocab, d_model, n_layer, n_head,
                                d_inner, max_pos, name="lm"):
    """A randomly initialized transformer-LM weight dict with exactly
    the keys the ``make_transformer_lm_*_step_fn`` builders read —
    the one place the key/shape schema lives for benches and tests."""
    w = {name + "_word_emb": rng.randn(vocab, d_model) * 0.1,
         name + "_pos_emb": rng.randn(max_pos, d_model) * 0.1,
         name + "_head_w": rng.randn(d_model, vocab) * 0.1,
         name + "_head_b": np.zeros(vocab)}
    for i in range(n_layer):
        p = "%s_dec_%d" % (name, i)
        for nm, shp in (("_att_q", (d_model, d_model)),
                        ("_att_k", (d_model, d_model)),
                        ("_att_v", (d_model, d_model)),
                        ("_att_out", (d_model, d_model)),
                        ("_ffn_fc0", (d_model, d_inner)),
                        ("_ffn_fc1", (d_inner, d_model))):
            w[p + nm + "_w"] = rng.randn(*shp) * 0.1
            w[p + nm + "_b"] = np.zeros(shp[1])
        for ln in ("_ln1", "_ln2"):
            w[p + ln + "_scale"] = np.ones(d_model)
            w[p + ln + "_bias"] = np.zeros(d_model)
    return {k: np.asarray(v, "float32") for k, v in w.items()}


def make_program_logits_fn(program, state, feed_names, logits_name):
    """Lower an inference program into ``logits_fn(feeds_dict) -> logits``
    for use inside the decode loop.  ``state``: persistable name->array
    (trained params)."""
    from paddle_tpu.core import lowering

    block = program.global_block()
    fn = lowering.lower_block(block, feed_names, [logits_name], [])

    def logits_fn(feeds):
        fetches, _ = fn(dict(state), feeds)
        return fetches[0]

    return logits_fn


def _beam_core(step, state0, B, K, bos_id, eos_id, max_len, length_penalty):
    """Shared beam bookkeeping for the full-prefix and KV-cached paths.

    ``step(state, tokens_flat [B*K, max_len], t) -> (logits [B*K, V],
    state)`` returns the next-token logits for loop position ``t``
    (i.e. conditioned on the prefix through ``t - 1``); ``state`` is an
    arbitrary pytree (None for stateless full-prefix, per-layer KV
    caches for the cached path) whose leaves carry a leading B*K axis —
    after each selection its rows are gathered by the winning parents.
    """
    import jax
    import jax.numpy as jnp

    NEG = -1e9
    tokens0 = jnp.full((B, K, max_len), eos_id, dtype="int32")
    tokens0 = tokens0.at[:, :, 0].set(bos_id)
    scores0 = jnp.where(jnp.arange(K)[None, :] == 0, 0.0, NEG) * jnp.ones((B, 1))
    finished0 = jnp.zeros((B, K), dtype=bool)

    def body(t, carry):
        tokens, scores, finished, st = carry
        flat = tokens.reshape(B * K, max_len)
        logits, st = step(st, flat, t)
        logp = jax.nn.log_softmax(logits, axis=-1).reshape(B, K, -1)
        V = logp.shape[-1]
        # finished beams may only extend with EOS at zero cost
        eos_only = jnp.full((V,), NEG).at[eos_id].set(0.0)
        logp = jnp.where(finished[..., None], eos_only[None, None, :], logp)
        total = scores[..., None] + logp  # [B, K, V]
        top_scores, top_idx = jax.lax.top_k(total.reshape(B, K * V), K)
        parent = top_idx // V  # [B, K]
        tok = (top_idx % V).astype("int32")
        rows = (jnp.arange(B)[:, None] * K + parent).reshape(-1)
        tokens = jnp.take_along_axis(tokens, parent[..., None], axis=1)
        tokens = tokens.at[:, :, t].set(tok)
        finished = jnp.take_along_axis(finished, parent, axis=1) | (tok == eos_id)
        st = jax.tree.map(lambda c: c[rows], st)
        return tokens, top_scores, finished, st

    tokens, scores, finished, _ = jax.lax.fori_loop(
        1, max_len, body, (tokens0, scores0, finished0, state0)
    )
    if length_penalty > 0.0:
        lengths = jnp.sum((tokens != eos_id).astype("float32"), axis=-1) + 1.0
        scores = scores / (lengths ** length_penalty)
        order = jnp.argsort(-scores, axis=-1)
        tokens = jnp.take_along_axis(tokens, order[..., None], axis=1)
        scores = jnp.take_along_axis(scores, order, axis=1)
    return tokens, scores


def beam_search(
    logits_fn: Callable,
    src: np.ndarray,
    bos_id: int,
    eos_id: int,
    beam_size: int = 4,
    max_len: int = 16,
    src_feed_name: str = "src",
    tgt_feed_name: str = "tgt",
    length_penalty: float = 0.0,
    extra_feeds: Optional[dict] = None,
):
    """Returns (tokens [B, beam, max_len], scores [B, beam]) sorted best
    first.  ``logits_fn`` maps {src, tgt [N, max_len]} -> [N, max_len, V].
    """
    import jax.numpy as jnp

    src = jnp.asarray(src)
    B, K = src.shape[0], beam_size
    src_tiled = jnp.repeat(src, K, axis=0)  # [B*K, S]
    extra_tiled = {
        k: jnp.repeat(jnp.asarray(v), K, axis=0) for k, v in (extra_feeds or {}).items()
    }

    def step(state, flat, t):
        feeds = {src_feed_name: src_tiled, tgt_feed_name: flat}
        feeds.update(extra_tiled)
        logits = logits_fn(feeds)  # [B*K, T, V]
        return logits[:, t - 1, :], state

    return _beam_core(step, None, B, K, bos_id, eos_id, max_len, length_penalty)


def greedy_search(logits_fn, src, bos_id, eos_id, max_len=16, **kwargs):
    """Greedy = beam 1; returns (tokens [B, max_len], scores [B])."""
    tokens, scores = beam_search(
        logits_fn, src, bos_id, eos_id, beam_size=1, max_len=max_len, **kwargs
    )
    return tokens[:, 0], scores[:, 0]


# ---------------------------------------------------------------------------
# KV-cached decoding
# ---------------------------------------------------------------------------
def beam_search_cached(
    step_fn: Callable,
    init_cache,
    batch: int,
    bos_id: int,
    eos_id: int,
    beam_size: int = 4,
    max_len: int = 16,
    length_penalty: float = 0.0,
):
    """Beam search with a KV cache carried through the compiled loop.

    ``step_fn(cache, tokens [N] int32, t) -> (logits [N, V], cache)``:
    consume the token at position ``t`` and return logits for position
    ``t + 1``; cache leaves carry a leading ``N = batch * beam`` axis
    so the beam reorder can gather rows by parent.  ``init_cache``: the
    zeroed cache pytree (leaves ``[N, ...]``).  One lax.fori_loop, no
    host round-trips; each step is O(prefix) instead of the
    full-prefix re-run's O(prefix^2)."""

    def step(cache, flat, t):
        return step_fn(cache, flat[:, t - 1], t - 1)

    return _beam_core(step, init_cache, batch, beam_size, bos_id, eos_id,
                      max_len, length_penalty)


def greedy_search_cached(step_fn, init_cache, batch, bos_id, eos_id,
                         max_len=16, **kwargs):
    """Greedy = beam 1 on the cached path; returns ([B, max_len], [B])."""
    tokens, scores = beam_search_cached(
        step_fn, init_cache, batch, bos_id, eos_id, beam_size=1,
        max_len=max_len, **kwargs
    )
    return tokens[:, 0], scores[:, 0]


def make_transformer_lm_step_fn(
    state,
    vocab_size: int,
    d_model: int,
    n_layer: int,
    n_head: int,
    d_inner: int,
    max_len: int,
    name: str = "lm",
):
    """Build (step_fn, make_cache) for KV-cached decoding from a trained
    ``models.transformer.transformer_lm`` Program's weights.

    ``state``: persistable name -> array (the same dict
    ``make_program_logits_fn`` takes).  Mirrors the Program math exactly
    — post-LN blocks (eps 1e-5), exact (non-tanh) gelu FFN, per-head
    scaled dot product — on an incrementally updated ``[N, H, T, Dh]``
    key/value cache per layer, so cached decode == full-prefix decode
    bit-for-tolerance (parity-tested).

    Returns ``(step_fn, make_cache)`` where ``make_cache(n_rows)``
    allocates the zeroed cache for ``n_rows = batch * beam`` lanes.
    """
    import jax
    import jax.numpy as jnp

    d_head = d_model // n_head
    W = {k: jnp.asarray(v) for k, v in state.items()}

    def make_cache(n_rows: int):
        return [
            {
                "k": jnp.zeros((n_rows, n_head, max_len, d_head), "float32"),
                "v": jnp.zeros((n_rows, n_head, max_len, d_head), "float32"),
            }
            for _ in range(n_layer)
        ]

    scale = 1.0 / float(np.sqrt(d_head))

    def step_fn(cache, tokens, t):
        # tokens [N] int32; t: position being consumed
        x = W[name + "_word_emb"][tokens] + W[name + "_pos_emb"][t]
        return _lm_forward_one(W, name, cache, x, t, None, n_layer,
                               n_head, d_head, d_model, scale)

    return step_fn, make_cache


def _lm_forward_one(W, name, cache, x, t, ts, n_layer, n_head, d_head,
                    d_model, scale, kv_int8=False):
    """One incremental transformer-LM forward shared by the scalar-``t``
    and slot-pooled (per-row ``ts``) step fns.  Exactly one of ``t``
    (scalar loop position, all rows aligned) / ``ts`` ([N] int32, each
    row at its own position) is not None; the cache T axis is read from
    the cache itself so one builder serves every length rung.

    ``kv_int8`` (pooled path only): the cache stores K/V rows int8 with
    per-(slot, head, position) fp32 scales as sibling ``k_scale``/
    ``v_scale`` leaves — each fresh row is quantized as it is written
    (quantize-on-write) and the whole cache is dequantized in registers
    at attention time (dequant-at-attend), so HBM traffic moves int8
    bytes while the attention math stays fp32."""
    import jax
    import jax.numpy as jnp

    T = cache[0]["k"].shape[2]
    n = x.shape[0]
    if ts is None:
        pos_ok = (jnp.arange(T) <= t)[None, None, :]       # [1,1,T]
        row_t = None
    else:
        pos_ok = (jnp.arange(T)[None, :] <= ts[:, None])[:, None, :]  # [N,1,T]
        row_t = (jnp.arange(T)[None, :] == ts[:, None])    # [N,T]
    if kv_int8:
        from paddle_tpu.quant import dequantize_rows, quantize_rows
    new_cache = []
    for i in range(n_layer):
        p = "%s_dec_%d" % (name, i)
        q = _fc(W, x, p + "_att_q").reshape(n, n_head, d_head)
        k = _fc(W, x, p + "_att_k").reshape(n, n_head, d_head)
        v = _fc(W, x, p + "_att_v").reshape(n, n_head, d_head)
        if kv_int8:
            # quantize-on-write: one absmax scale per fresh (row, head)
            kq, ks = quantize_rows(k)                      # [N,H] scales
            vq, vs = quantize_rows(v)
            sel = row_t[:, None, :, None]                  # [N,1,T,1]
            ssel = row_t[:, None, :]                       # [N,1,T]
            kc = jnp.where(sel, kq[:, :, None, :], cache[i]["k"])
            vc = jnp.where(sel, vq[:, :, None, :], cache[i]["v"])
            ksc = jnp.where(ssel, ks[:, :, None], cache[i]["k_scale"])
            vsc = jnp.where(ssel, vs[:, :, None], cache[i]["v_scale"])
            new_cache.append({"k": kc, "k_scale": ksc,
                              "v": vc, "v_scale": vsc})
            # dequant-at-attend: int8 bytes leave HBM, fp32 enters the
            # einsums
            kcf = dequantize_rows(kc, ksc)
            vcf = dequantize_rows(vc, vsc)
        else:
            if ts is None:
                kc = jax.lax.dynamic_update_index_in_dim(
                    cache[i]["k"], k, t, axis=2)
                vc = jax.lax.dynamic_update_index_in_dim(
                    cache[i]["v"], v, t, axis=2)
            else:
                # per-row scatter: each lane writes its OWN position —
                # the one-hot select is O(cache) like the attention
                sel = row_t[:, None, :, None]              # [N,1,T,1]
                kc = jnp.where(sel, k[:, :, None, :], cache[i]["k"])
                vc = jnp.where(sel, v[:, :, None, :], cache[i]["v"])
            new_cache.append({"k": kc, "v": vc})
            kcf, vcf = kc, vc
        scores = jnp.einsum("nhd,nhtd->nht", q, kcf) * scale
        scores = jnp.where(pos_ok, scores, -1e9)
        w = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("nht,nhtd->nhd", w, vcf).reshape(n, d_model)
        att = _fc(W, ctx, p + "_att_out")
        x = _ln(W, x + att, p + "_ln1")
        h = jax.nn.gelu(_fc(W, x, p + "_ffn_fc0"), approximate=False)
        x = _ln(W, x + _fc(W, h, p + "_ffn_fc1"), p + "_ln2")
    logits = _fc(W, x, name + "_head")
    return logits, new_cache


def _fc(W, x, pname):
    return x @ W[pname + "_w"] + W[pname + "_b"]


def _ln(W, x, pname):
    import jax.numpy as jnp

    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mean) / jnp.sqrt(var + 1e-5)
    return y * W[pname + "_scale"] + W[pname + "_bias"]


def make_transformer_lm_pooled_step_fn(
    state,
    vocab_size: int,
    d_model: int,
    n_layer: int,
    n_head: int,
    d_inner: int,
    name: str = "lm",
    kv_dtype: str = "fp32",
):
    """The slot-pool variant of :func:`make_transformer_lm_step_fn`.

    Continuous batching decodes a POOL of sequences that are each at a
    DIFFERENT position (a request admitted mid-flight starts its prefill
    while its neighbors are deep into generation), so the step consumes
    per-row positions: ``step_fn(cache, tokens [N] int32, ts [N] int32)
    -> (logits [N, V], cache)`` where row ``i`` consumes ``tokens[i]``
    at position ``ts[i]`` (cache row ``i`` updated at ``ts[i]``; its
    attention masked to positions ``<= ts[i]``).

    The cache T axis is read from the cache arrays themselves, so one
    step fn serves every length rung of the slot pool's bucket ladder:
    ``make_cache(n_rows, seq_len)`` allocates the zeroed pytree for one
    (slot-rung, length-rung) pair.  Math is identical to the scalar-t
    builder — with all rows at the same position the two are exactly
    equal (parity-tested in tests/test_seq2seq_decode.py).

    The pool relies on a write-before-read invariant instead of cache
    zeroing on slot reuse: a sequence at position ``ts`` has itself
    written every cache position ``<= ts`` (prefill consumes each prompt
    token through the same step), and the mask hides ``> ts`` — stale
    rows from a previous occupant are never read.

    ``kv_dtype="int8"`` stores the cache int8 (per-slot-per-head
    scales as sibling ``k_scale``/``v_scale`` [N, H, T] fp32 leaves,
    quantize-on-write / dequant-at-attend — see ``_lm_forward_one``),
    roughly quartering per-slot KV bytes so a fixed HBM budget holds
    ~2x+ the concurrent sequences.  The scale leaves keep the slot
    axis leading and the sequence axis last, so the slot pool's
    ``resize``/``extract_kv``/``admit_prefix`` carry them exactly like
    the K/V leaves (``kv_leaf_seq_axis`` qualifies them) — prefix
    caching and speculative decode compose unchanged.
    """
    import jax.numpy as jnp

    kv_dtype = normalize_kv_dtype(kv_dtype)
    kv_int8 = kv_dtype == "int8"
    d_head = d_model // n_head
    W = {k: jnp.asarray(v) for k, v in state.items()}
    scale = 1.0 / float(np.sqrt(d_head))

    def make_cache(n_rows: int, seq_len: int):
        if kv_int8:
            return [
                {
                    "k": jnp.zeros((n_rows, n_head, seq_len, d_head),
                                   "int8"),
                    "k_scale": jnp.zeros((n_rows, n_head, seq_len),
                                         "float32"),
                    "v": jnp.zeros((n_rows, n_head, seq_len, d_head),
                                   "int8"),
                    "v_scale": jnp.zeros((n_rows, n_head, seq_len),
                                         "float32"),
                }
                for _ in range(n_layer)
            ]
        return [
            {
                "k": jnp.zeros((n_rows, n_head, seq_len, d_head), "float32"),
                "v": jnp.zeros((n_rows, n_head, seq_len, d_head), "float32"),
            }
            for _ in range(n_layer)
        ]

    def step_fn(cache, tokens, ts):
        x = W[name + "_word_emb"][tokens] + W[name + "_pos_emb"][ts]
        return _lm_forward_one(W, name, cache, x, None, ts, n_layer,
                               n_head, d_head, d_model, scale,
                               kv_int8=kv_int8)

    return step_fn, make_cache


def make_transformer_lm_pooled_verify_fn(
    state,
    vocab_size: int,
    d_model: int,
    n_layer: int,
    n_head: int,
    d_inner: int,
    name: str = "lm",
    kv_dtype: str = "fp32",
):
    """The K-wide teacher-forced forward for speculative verification.

    ``verify_fn(cache, tokens [S, K] int32, ts [S] int32) -> (logits
    [S, K, V], cache)``: row ``i`` consumes ``tokens[i, j]`` at position
    ``ts[i] + j`` for every ``j`` in ONE call — exactly the math of K
    sequential :func:`make_transformer_lm_pooled_step_fn` steps (same
    weights dict, same post-LN/gelu blocks), with causal masking among
    the K fresh positions, so ``argmax(logits[i, j])`` is bit-identical
    to the token the sequential path would produce after consuming
    ``tokens[i, :j + 1]``.  That equality is what makes greedy-exact
    speculative acceptance output-identical (parity-pinned in
    tests/test_prefix_cache.py).

    Positions are clamped to the cache T axis like the sequential step
    clamps its buffer indices; a clamped lane is garbage-in-garbage-out
    but such lanes are inactive/finished and their results are never
    committed.  The K fresh K/V rows are scattered into the cache BEFORE
    attention (write-before-read, same invariant as the pooled step), so
    position ``ts + j`` attends to the just-written rows ``ts .. ts + j``.

    ``kv_dtype`` must match the step fn the cache was built for: with
    ``"int8"`` each fresh row is quantized EXACTLY like the sequential
    step quantizes it (same per-row absmax), scattered as int8 with its
    scale, and the cache dequantized at attention time — quantization
    is deterministic, so greedy-exact acceptance still holds
    bit-for-bit against the int8 sequential path.
    """
    import jax
    import jax.numpy as jnp

    kv_dtype = normalize_kv_dtype(kv_dtype)
    kv_int8 = kv_dtype == "int8"
    d_head = d_model // n_head
    W = {k: jnp.asarray(v) for k, v in state.items()}
    scale = 1.0 / float(np.sqrt(d_head))
    if kv_int8:
        from paddle_tpu.quant import dequantize_rows, quantize_rows

    def verify_fn(cache, tokens, ts):
        S, K = tokens.shape
        T = cache[0]["k"].shape[2]
        p = jnp.minimum(ts[:, None] + jnp.arange(K)[None, :], T - 1)
        x = W[name + "_word_emb"][tokens] + W[name + "_pos_emb"][p]
        sel = (jnp.arange(T)[None, None, :] == p[:, :, None])  # [S,K,T]
        touched = sel.any(axis=1)[:, None, :, None]            # [S,1,T,1]
        touched_s = sel.any(axis=1)[:, None, :]                # [S,1,T]
        pos_ok = (jnp.arange(T)[None, None, None, :]
                  <= p[:, :, None, None])                      # [S,K,1,T]
        new_cache = []
        for i in range(n_layer):
            pfx = "%s_dec_%d" % (name, i)
            q = _fc(W, x, pfx + "_att_q").reshape(S, K, n_head, d_head)
            k = _fc(W, x, pfx + "_att_k").reshape(S, K, n_head, d_head)
            v = _fc(W, x, pfx + "_att_v").reshape(S, K, n_head, d_head)
            # scatter the K fresh rows at positions p: the one-hot
            # einsum reduces to an exact copy for the (distinct) live
            # positions; clamp collisions only happen on lanes past
            # their buffer, whose rows are never read back
            selk = sel.astype(jnp.float32)
            if kv_int8:
                # quantize each fresh row the way the sequential step
                # does (per-row absmax) BEFORE the scatter: int8 codes
                # are exact small integers in fp32, so the one-hot
                # einsum copy round-trips them bit-identically
                kq, ks = quantize_rows(k)                  # [S,K,H]
                vq, vs = quantize_rows(v)
                kc = jnp.where(
                    touched,
                    jnp.clip(jnp.einsum("skt,skhd->shtd", selk,
                                        kq.astype(jnp.float32)),
                             -127.0, 127.0).astype(jnp.int8),
                    cache[i]["k"])
                vc = jnp.where(
                    touched,
                    jnp.clip(jnp.einsum("skt,skhd->shtd", selk,
                                        vq.astype(jnp.float32)),
                             -127.0, 127.0).astype(jnp.int8),
                    cache[i]["v"])
                ksc = jnp.where(touched_s,
                                jnp.einsum("skt,skh->sht", selk, ks),
                                cache[i]["k_scale"])
                vsc = jnp.where(touched_s,
                                jnp.einsum("skt,skh->sht", selk, vs),
                                cache[i]["v_scale"])
                new_cache.append({"k": kc, "k_scale": ksc,
                                  "v": vc, "v_scale": vsc})
                kcf = dequantize_rows(kc, ksc)
                vcf = dequantize_rows(vc, vsc)
            else:
                kc = jnp.where(touched,
                               jnp.einsum("skt,skhd->shtd", selk, k),
                               cache[i]["k"])
                vc = jnp.where(touched,
                               jnp.einsum("skt,skhd->shtd", selk, v),
                               cache[i]["v"])
                new_cache.append({"k": kc, "v": vc})
                kcf, vcf = kc, vc
            scores = jnp.einsum("skhd,shtd->skht", q, kcf) * scale
            scores = jnp.where(pos_ok, scores, -1e9)
            w = jax.nn.softmax(scores, axis=-1)
            ctx = jnp.einsum("skht,shtd->skhd", w, vcf).reshape(S, K, d_model)
            att = _fc(W, ctx, pfx + "_att_out")
            x = _ln(W, x + att, pfx + "_ln1")
            h = jax.nn.gelu(_fc(W, x, pfx + "_ffn_fc0"), approximate=False)
            x = _ln(W, x + _fc(W, h, pfx + "_ffn_fc1"), pfx + "_ln2")
        logits = _fc(W, x, name + "_head")
        return logits, new_cache

    return verify_fn


# ---------------------------------------------------------------------------
# Slot-pool decode: the fused multi-token chunk + admit executables
# ---------------------------------------------------------------------------
def make_slot_decode_fns(step_fn, eos_id: int, steps: int,
                         draft_step_fn=None):
    """Build the three pure functions the serving slot pool compiles per
    (slot-rung, length-rung) pair: ``chunk(state) -> state`` advancing
    every active slot by up to ``steps`` tokens in ONE device dispatch
    (a ``fori_loop`` — multi-step dispatch amortizes host overhead
    between scheduler interventions), ``admit(state, slot_mask,
    prompt, prompt_len, total_len) -> state`` seating one request into a
    free slot, and ``release(state, slot_mask) -> state`` deactivating
    slots mid-flight (deadline abort) so their lanes stop advancing.

    The pool state is a dict pytree (every leaf's axis 0 is the slot):

    * ``cache``    — the step fn's KV pytree (T axis read by the step)
    * ``tokens``   — [S, T] int32, position-indexed token buffer
    * ``pos``      — [S] int32, tokens consumed so far (the step eats
      index ``pos`` and produces the token for ``pos + 1``)
    * ``prompt_len``/``total_len`` — [S] int32 per-slot prompt size and
      overall length cap (prompt + generated <= total_len <= T)
    * ``active``/``finished`` — [S] bool scheduler flags
    * ``n_gen``    — [S] int32 generated-token count (prefill/decode
      ratio accounting reads the deltas host-side)

    Prefill and decode are the SAME step: while ``pos + 1 <
    prompt_len`` the produced token is discarded in favor of the stored
    prompt token (teacher forcing), so a freshly admitted prompt fills
    its cache inside the running batch — no separate prefill executable,
    no second compiled shape.  A slot finishes when it emits ``eos_id``
    or reaches ``total_len``; inactive slots are fully masked (their
    ``pos`` does not advance) and cost only the wasted lane math the
    bucket ladder already prices in.

    Extra state leaves pass through untouched (dict-copy semantics), so
    the speculative pool's ``spec`` flag and ``draft_cache`` ride the
    same executables.  With ``draft_step_fn`` the plain chunk also
    teacher-forces each consumed token through the draft model, keeping
    ``state["draft_cache"]`` position-synced with the target — a slot
    that alternates plain and speculative rounds never sees a stale
    draft cache (write-before-read covers the rest).  ``admit`` grows an
    optional trailing ``spec_flag`` scalar marking the seated slot
    speculative.
    """
    import jax
    import jax.numpy as jnp

    def _body(_, state):
        tokens = state["tokens"]
        pos = state["pos"]
        active = state["active"]
        S, T = tokens.shape
        rows = jnp.arange(S)
        tok_in = tokens[rows, jnp.minimum(pos, T - 1)]
        logits, cache = step_fn(state["cache"], tok_in, pos)
        nxt = jnp.argmax(logits, axis=-1).astype("int32")
        write_idx = jnp.minimum(pos + 1, T - 1)
        in_prefill = (pos + 1) < state["prompt_len"]
        do_write = active & ~in_prefill
        cur = tokens[rows, write_idx]
        tokens = tokens.at[rows, write_idx].set(
            jnp.where(do_write, nxt, cur))
        newly_fin = do_write & (
            (nxt == eos_id) | ((pos + 2) >= state["total_len"]))
        out = dict(state)
        out.update(
            cache=cache,
            tokens=tokens,
            pos=jnp.where(active, pos + 1, pos),
            active=active & ~newly_fin,
            finished=state["finished"] | newly_fin,
            n_gen=state["n_gen"] + do_write.astype("int32"))
        if draft_step_fn is not None:
            _, out["draft_cache"] = draft_step_fn(
                state["draft_cache"], tok_in, pos)
        return out

    def chunk(state):
        return jax.lax.fori_loop(0, steps, _body, state)

    def admit(state, slot_mask, prompt, prompt_len, total_len,
              spec_flag=None):
        # slot_mask [S] bool (one admitted slot), prompt [T] int32
        # (padded host-side), prompt_len/total_len () int32 scalars.
        # The cache passes through UNTOUCHED: the write-before-read
        # invariant (see make_transformer_lm_pooled_step_fn) makes
        # zeroing a reused slot's rows unnecessary.
        mask = slot_mask
        out = dict(state)
        out.update(
            tokens=jnp.where(mask[:, None], prompt[None, :],
                             state["tokens"]),
            pos=jnp.where(mask, 0, state["pos"]),
            prompt_len=jnp.where(mask, prompt_len, state["prompt_len"]),
            total_len=jnp.where(mask, total_len, state["total_len"]),
            active=state["active"] | mask,
            finished=state["finished"] & ~mask,
            n_gen=jnp.where(mask, 0, state["n_gen"]))
        if spec_flag is not None:
            out["spec"] = jnp.where(mask, spec_flag, state["spec"])
        return out

    def release(state, slot_mask):
        # deactivate without finishing: the slot becomes seatable again
        # (its request was aborted host-side); tokens/cache stay — the
        # write-before-read invariant protects the next occupant
        out = dict(state)
        out.update(
            active=state["active"] & ~slot_mask,
            finished=state["finished"] & ~slot_mask)
        return out

    return chunk, admit, release


# ---------------------------------------------------------------------------
# Prefix KV installation (serving.prefix_cache's device half)
# ---------------------------------------------------------------------------
def kv_leaf_seq_axis(shape, n_slots: int, seq_len: int):
    """The sequence axis of a per-slot KV-cache leaf, or None when the
    leaf carries no per-slot sequence state (no leading slot axis of
    ``n_slots``, or no axis of size ``seq_len`` past it).

    Convention: the LAST axis of size ``seq_len`` that is not the final
    axis, else the final axis — the transformer cache is ``[S, H, T,
    Dh]`` (T at -2, robust to an ``H == T`` or ``Dh == T`` coincidence)
    and simple per-position buffers are ``[S, T]`` (T final).  Both the
    host extract/pad side and the traced install side resolve the axis
    through this one function so they can never disagree.
    """
    if len(shape) < 2 or shape[0] != n_slots:
        return None
    inner = tuple(shape[1:])
    cands = [i for i, d in enumerate(inner) if d == seq_len]
    if not cands:
        return None
    non_final = [i for i in cands if i != len(inner) - 1]
    return (non_final[-1] if non_final else cands[-1]) + 1


def make_prefix_admit_fn(admit_fn):
    """Wrap a :func:`make_slot_decode_fns` ``admit`` with shared-prefix
    KV installation: ``admit_prefix(state, slot_mask, prompt,
    prompt_len, total_len, kv_leaves, prefix_len[, spec_flag])`` seats
    the request as usual, then overwrites the slot's first
    ``prefix_len`` cache positions with the retained KV blocks and
    starts ``pos`` at ``prefix_len`` — prefill resumes at the unmatched
    suffix.

    ``kv_leaves`` is the flattened leaf list of the state's KV subtrees
    (``cache`` plus ``draft_cache`` when present, in tree-flatten
    order), each leaf host-padded along its sequence axis to the
    state's length rung; non-qualifying positions carry a ``(1,)``
    dummy.  Qualification and the sequence axis are decided by STATIC
    shapes (:func:`kv_leaf_seq_axis`), so one compiled executable per
    rung pair serves every cached prefix length — ``prefix_len`` stays
    a dynamic scalar.  Positional embeddings are absolute, so retained
    rows are position-correct for any matching prompt.
    """
    import jax
    import jax.numpy as jnp

    def admit_prefix(state, slot_mask, prompt, prompt_len, total_len,
                     kv_leaves, prefix_len, spec_flag=None):
        if spec_flag is None:
            out = admit_fn(state, slot_mask, prompt, prompt_len,
                           total_len)
        else:
            out = admit_fn(state, slot_mask, prompt, prompt_len,
                           total_len, spec_flag)
        S, T = state["tokens"].shape
        keep = jnp.arange(T) < prefix_len
        sub = {"cache": out["cache"]}
        if "draft_cache" in out:
            sub["draft_cache"] = out["draft_cache"]
        leaves, treedef = jax.tree_util.tree_flatten(sub)
        new_leaves = []
        for cur, pre in zip(leaves, kv_leaves):
            ax = kv_leaf_seq_axis(cur.shape, S, T)
            if ax is None or tuple(pre.shape) != tuple(cur.shape[1:]):
                new_leaves.append(cur)
                continue
            kshape = [1] * cur.ndim
            kshape[ax] = T
            sel = (slot_mask.reshape((S,) + (1,) * (cur.ndim - 1))
                   & keep.reshape(kshape))
            new_leaves.append(
                jnp.where(sel, pre[None].astype(cur.dtype), cur))
        sub = jax.tree_util.tree_unflatten(treedef, new_leaves)
        out["cache"] = sub["cache"]
        if "draft_cache" in sub:
            out["draft_cache"] = sub["draft_cache"]
        out["pos"] = jnp.where(slot_mask, prefix_len, out["pos"])
        return out

    return admit_prefix
