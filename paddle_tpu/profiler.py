"""Profiler (reference: python/paddle/fluid/profiler.py:39-225 +
platform/profiler.cc RecordEvent / CUPTI DeviceTracer).

TPU-native: device-side tracing is jax.profiler (XPlane; view in
TensorBoard/xprof or chrome://tracing — the timeline.py analog is built
into xprof), host-side per-run timing is recorded by this module.
"""
from __future__ import annotations

import contextlib
import functools
import time
from collections import defaultdict
from typing import Dict, List, Optional

from paddle_tpu.monitor import spans as _mon_spans

__all__ = [
    "profiler", "start_profiler", "stop_profiler", "reset_profiler",
    "RecordEvent", "cuda_profiler", "start_jsonl_trace", "stop_jsonl_trace",
    "emit_trace_event", "jsonl_trace", "last_device_trace",
]

_host_events: Dict[str, List[float]] = defaultdict(list)
_active_trace_dir: Optional[str] = None
_active_trace_anchor: Optional[float] = None  # wall clock at start_trace
_last_trace: Optional[tuple] = None  # (dir, anchor) of the last finished trace
_ERROR_SUFFIX = " (error)"  # table key for spans that exited via exception


class RecordEvent:
    """Host-side RAII timing marker (reference: profiler.h:81).

    Context manager OR decorator::

        with RecordEvent("step"): ...

        @RecordEvent("step")
        def step(...): ...

    Spans that exit via exception aggregate under ``"<name> (error)"``
    in the stop_profiler() table and carry ``error=True`` in any active
    monitor trace session, so failed runs are distinguishable.
    """

    def __init__(self, name: str):
        self.name = name

    def __call__(self, fn):
        # a FRESH instance per invocation: the decorated function may be
        # reentrant or called from several threads, and _t0 lives on self
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with RecordEvent(self.name):
                return fn(*args, **kwargs)

        return wrapper

    def __enter__(self):
        self._t0 = time.perf_counter()
        # own span id on the parent stack while the body runs: spans
        # recorded inside (predictor hop, executor phases) nest under
        # this block with a real parent edge
        self._sid = (
            _mon_spans.push_parent() if _mon_spans.recording() else None)
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        error = exc_type is not None
        _host_events[self.name + _ERROR_SUFFIX if error else self.name].append(dur)
        if self._sid is not None:
            _mon_spans.pop_parent()
        _mon_spans.record_span(
            self.name, self._t0, dur, cat="record_event", error=error,
            span_id=self._sid)
        return False


def start_profiler(state: str = "All", trace_dir: Optional[str] = None):
    """reference: profiler.py start_profiler / EnableProfiler.

    Idempotent: a second start (or a start after a crashed run) first
    stops any device trace this module previously started (via
    reset_profiler), so jax.profiler never sees a double start.
    """
    global _active_trace_dir, _active_trace_anchor
    reset_profiler()
    if trace_dir:
        import jax

        # exception-safe: _active_trace_dir is only set AFTER the trace
        # actually started, so a failed start leaves no dangling state
        # for stop_profiler()/reset_profiler() to trip over.  The wall
        # clock is read just before the start so device-trace timestamps
        # (µs relative to session start) can be re-anchored onto the
        # host span timebase by monitor.export_chrome_trace.
        anchor = time.time()
        jax.profiler.start_trace(trace_dir)
        _active_trace_dir = trace_dir
        _active_trace_anchor = anchor


def stop_profiler(sorted_key: str = "total", profile_path: Optional[str] = None):
    """reference: profiler.py stop_profiler — prints the per-event table."""
    global _active_trace_dir, _active_trace_anchor, _last_trace
    if _active_trace_dir is not None:
        _last_trace = (_active_trace_dir, _active_trace_anchor)
        _active_trace_dir = _active_trace_anchor = None
        import jax

        try:
            jax.profiler.stop_trace()
        except RuntimeError:
            pass  # trace already gone (e.g. a reset raced us) — still print
    rows = []
    for name, ts in _host_events.items():
        rows.append((name, len(ts), sum(ts), max(ts), sum(ts) / len(ts)))
    key_idx = {"total": 2, "max": 3, "ave": 4, "calls": 1}.get(sorted_key, 2)
    rows.sort(key=lambda r: r[key_idx], reverse=True)
    lines = ["%-40s %8s %12s %12s %12s" % ("Event", "Calls", "Total(s)", "Max(s)", "Ave(s)")]
    for name, calls, total, mx, ave in rows:
        lines.append("%-40s %8d %12.6f %12.6f %12.6f" % (name, calls, total, mx, ave))
    report = "\n".join(lines)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(report)
    else:
        print(report)
    return rows


def reset_profiler():
    """Clear host events AND stop any device trace this module started.

    The pre-fix behavior left ``_active_trace_dir`` dangling: a
    ``start_profiler(trace_dir=...)`` + ``reset_profiler()`` +
    ``stop_profiler()`` sequence (or two back-to-back starts) called
    ``jax.profiler.stop_trace()``/``start_trace()`` against a trace the
    reset never cleared.  Reset now owns the whole teardown, so start
    and reset are idempotent and exception-safe.
    """
    global _active_trace_dir, _active_trace_anchor, _last_trace
    _host_events.clear()
    if _active_trace_dir is not None:
        _last_trace = (_active_trace_dir, _active_trace_anchor)
        _active_trace_dir = _active_trace_anchor = None
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass  # a reset must never raise over a half-dead trace


def last_device_trace() -> Optional[tuple]:
    """``(trace_dir, wall_anchor)`` for the most recently finished
    jax.profiler trace this module started — the time-alignment hint
    ``monitor.export_chrome_trace(device_trace_dir=...)`` consumes.
    The running trace is reported too (export-while-tracing reads a
    partial dir, which the loader tolerates)."""
    if _active_trace_dir is not None:
        return (_active_trace_dir, _active_trace_anchor)
    return _last_trace


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             profile_path: Optional[str] = None, trace_dir: Optional[str] = None):
    """reference: profiler.py:127 context manager."""
    start_profiler(state, trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


# ---------------------------------------------------------------------------
# JSONL event trace — one JSON object per line, for host-side subsystems
# that emit discrete events rather than RAII spans (serving batches, reader
# stalls, PS rounds).  Complements RecordEvent: RecordEvent aggregates into
# the stop_profiler() table, the JSONL sink keeps every event with its
# wall-clock timestamp so latency tails and occupancy histograms can be
# reconstructed offline.
# ---------------------------------------------------------------------------
_jsonl_sink = None  # (path, file handle, lock)


def start_jsonl_trace(path: str):
    """Open ``path`` and route emit_trace_event() lines to it (append
    mode, one JSON object per line).  Returns the path."""
    global _jsonl_sink
    import threading

    stop_jsonl_trace()
    _jsonl_sink = (path, open(path, "a"), threading.Lock())
    return path


def stop_jsonl_trace() -> Optional[str]:
    """Close the active JSONL sink; returns its path (or None)."""
    global _jsonl_sink
    if _jsonl_sink is None:
        return None
    path, fh, lock = _jsonl_sink
    _jsonl_sink = None
    with lock:
        fh.close()
    return path


def emit_trace_event(event: dict) -> None:
    """Write one event to the active JSONL sink (no-op when none is
    active).  A wall-clock ``ts`` field is stamped in unless the caller
    already provided one; the event must be JSON-serializable."""
    sink = _jsonl_sink
    if sink is None:
        return
    import json

    _, fh, lock = sink
    rec = dict(event)
    rec.setdefault("ts", time.time())
    line = json.dumps(rec)
    with lock:
        if not fh.closed:
            fh.write(line + "\n")
            fh.flush()


@contextlib.contextmanager
def jsonl_trace(path: str):
    """Context manager form of start/stop_jsonl_trace."""
    start_jsonl_trace(path)
    try:
        yield path
    finally:
        stop_jsonl_trace()


@contextlib.contextmanager
def cuda_profiler(*a, **k):
    """Legacy nvprof hook (reference: profiler.py:39) — device tracing on
    TPU goes through jax.profiler; kept as a no-op alias."""
    yield
