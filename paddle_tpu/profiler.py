"""Profiler (reference: python/paddle/fluid/profiler.py:39-225 +
platform/profiler.cc RecordEvent / CUPTI DeviceTracer).

TPU-native: device-side tracing is jax.profiler (XPlane; view in
TensorBoard/xprof or chrome://tracing — the timeline.py analog is built
into xprof), host-side per-run timing is recorded by this module.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, List, Optional

__all__ = [
    "profiler", "start_profiler", "stop_profiler", "reset_profiler",
    "RecordEvent", "cuda_profiler", "start_jsonl_trace", "stop_jsonl_trace",
    "emit_trace_event", "jsonl_trace",
]

_host_events: Dict[str, List[float]] = defaultdict(list)
_active_trace_dir: Optional[str] = None


class RecordEvent:
    """Host-side RAII timing marker (reference: profiler.h:81)."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        _host_events[self.name].append(time.perf_counter() - self._t0)
        return False


def start_profiler(state: str = "All", trace_dir: Optional[str] = None):
    """reference: profiler.py start_profiler / EnableProfiler."""
    global _active_trace_dir
    reset_profiler()
    if trace_dir:
        import jax

        jax.profiler.start_trace(trace_dir)
        _active_trace_dir = trace_dir


def stop_profiler(sorted_key: str = "total", profile_path: Optional[str] = None):
    """reference: profiler.py stop_profiler — prints the per-event table."""
    global _active_trace_dir
    if _active_trace_dir is not None:
        import jax

        jax.profiler.stop_trace()
        _active_trace_dir = None
    rows = []
    for name, ts in _host_events.items():
        rows.append((name, len(ts), sum(ts), max(ts), sum(ts) / len(ts)))
    key_idx = {"total": 2, "max": 3, "ave": 4, "calls": 1}.get(sorted_key, 2)
    rows.sort(key=lambda r: r[key_idx], reverse=True)
    lines = ["%-40s %8s %12s %12s %12s" % ("Event", "Calls", "Total(s)", "Max(s)", "Ave(s)")]
    for name, calls, total, mx, ave in rows:
        lines.append("%-40s %8d %12.6f %12.6f %12.6f" % (name, calls, total, mx, ave))
    report = "\n".join(lines)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(report)
    else:
        print(report)
    return rows


def reset_profiler():
    _host_events.clear()


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             profile_path: Optional[str] = None, trace_dir: Optional[str] = None):
    """reference: profiler.py:127 context manager."""
    start_profiler(state, trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


# ---------------------------------------------------------------------------
# JSONL event trace — one JSON object per line, for host-side subsystems
# that emit discrete events rather than RAII spans (serving batches, reader
# stalls, PS rounds).  Complements RecordEvent: RecordEvent aggregates into
# the stop_profiler() table, the JSONL sink keeps every event with its
# wall-clock timestamp so latency tails and occupancy histograms can be
# reconstructed offline.
# ---------------------------------------------------------------------------
_jsonl_sink = None  # (path, file handle, lock)


def start_jsonl_trace(path: str):
    """Open ``path`` and route emit_trace_event() lines to it (append
    mode, one JSON object per line).  Returns the path."""
    global _jsonl_sink
    import threading

    stop_jsonl_trace()
    _jsonl_sink = (path, open(path, "a"), threading.Lock())
    return path


def stop_jsonl_trace() -> Optional[str]:
    """Close the active JSONL sink; returns its path (or None)."""
    global _jsonl_sink
    if _jsonl_sink is None:
        return None
    path, fh, lock = _jsonl_sink
    _jsonl_sink = None
    with lock:
        fh.close()
    return path


def emit_trace_event(event: dict) -> None:
    """Write one event to the active JSONL sink (no-op when none is
    active).  A wall-clock ``ts`` field is stamped in unless the caller
    already provided one; the event must be JSON-serializable."""
    sink = _jsonl_sink
    if sink is None:
        return
    import json

    _, fh, lock = sink
    rec = dict(event)
    rec.setdefault("ts", time.time())
    line = json.dumps(rec)
    with lock:
        if not fh.closed:
            fh.write(line + "\n")
            fh.flush()


@contextlib.contextmanager
def jsonl_trace(path: str):
    """Context manager form of start/stop_jsonl_trace."""
    start_jsonl_trace(path)
    try:
        yield path
    finally:
        stop_jsonl_trace()


@contextlib.contextmanager
def cuda_profiler(*a, **k):
    """Legacy nvprof hook (reference: profiler.py:39) — device tracing on
    TPU goes through jax.profiler; kept as a no-op alias."""
    yield
