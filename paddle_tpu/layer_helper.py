"""LayerHelper: shared param-creation/op-append machinery for layers.

Reference: python/paddle/fluid/layer_helper.py:42.  Creates Parameters in
the startup+main programs (with initializer ops in startup) and appends
compute ops to the main program.
"""
from __future__ import annotations

import copy

from paddle_tpu import framework, initializer, unique_name
from paddle_tpu.core import types as core_types
from paddle_tpu.param_attr import ParamAttr

__all__ = ["LayerHelper"]


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name is not None else unique_name.generate(layer_type)

    @property
    def main_program(self) -> framework.Program:
        return framework.default_main_program()

    @property
    def startup_program(self) -> framework.Program:
        return framework.default_startup_program()

    def append_op(self, *args, **kwargs):
        return self.main_program.current_block().append_op(*args, **kwargs)

    def startup_op(self, *args, **kwargs):
        return self.startup_program.global_block().append_op(*args, **kwargs)

    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        return self.main_program.current_block().create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=core_types.canonical_dtype(dtype),
            stop_gradient=stop_gradient,
        )

    # alias used throughout layers
    create_tmp_variable = create_variable_for_type_inference

    def create_parameter(
        self,
        attr,
        shape,
        dtype,
        is_bias=False,
        default_initializer=None,
    ):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        attr = copy.deepcopy(attr)
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, "b" if is_bias else "w"]))
        if attr.initializer is None:
            if default_initializer is not None:
                attr.initializer = default_initializer
            elif is_bias:
                attr.initializer = initializer.Constant(0.0)
            else:
                attr.initializer = initializer.Xavier()
        shape = [int(s) for s in shape]
        dtype = core_types.canonical_dtype(dtype)
        # parameter in main program
        main_block = self.main_program.global_block()
        param = main_block.create_parameter(
            attr.name, shape, dtype, **{k: v for k, v in attr._to_kwargs().items() if k != "name"}
        )
        if framework.in_dygraph_mode():
            # eager init: run the initializer op immediately on the param
            # (reference dygraph creates VarBase params eagerly)
            param.stop_gradient = False
            attr.initializer(param, main_block)
            return param
        # mirror in startup program with its initializer op
        startup_block = self.startup_program.global_block()
        sparam = startup_block.create_parameter(
            attr.name, shape, dtype, **{k: v for k, v in attr._to_kwargs().items() if k != "name"}
        )
        attr.initializer(sparam, startup_block)
        return param

    def set_variable_initializer(self, var, init):
        """Create `var` in the startup program and initialize it there."""
        if framework.in_dygraph_mode():
            init(var, var.block)
            return var
        startup_block = self.startup_program.global_block()
        svar = startup_block.create_var(
            name=var.name, shape=var.shape, dtype=var.dtype, persistable=True
        )
        init(svar, startup_block)
        return var

    # ------------------------------------------------------------------
    def input(self, name="input"):
        return self.kwargs[name]

    @property
    def param_attr(self):
        return self.kwargs.get("param_attr")

    @property
    def bias_attr(self):
        return self.kwargs.get("bias_attr")

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        size = list(input_var.shape[dim_start:dim_end])
        bias_attr = self.bias_attr
        if bias_attr is False:
            return input_var
        b = self.create_parameter(bias_attr, shape=size, dtype=input_var.dtype, is_bias=True)
        tmp = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [tmp]},
            attrs={"axis": dim_start},
        )
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = dict(act)
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(type=act_type, inputs={"X": [input_var]}, outputs={"Out": [tmp]}, attrs=act)
        return tmp
