"""DataFeeder: sample lists -> feed dicts.

Reference: python/paddle/fluid/data_feeder.py (DataFeeder converts reader
output tuples into LoDTensor feed dicts).  TPU version produces numpy
batches (padded dense); ragged sequence inputs use the padded+length
encoding from ops/sequence_ops.py.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from paddle_tpu.core import types as core_types

__all__ = ["DataFeeder"]


class DataFeeder:
    def __init__(self, feed_list: Sequence, place=None, program=None):
        self.feed_vars = list(feed_list)
        self.place = place

    def feed(self, iterable) -> dict:
        """iterable: list of sample tuples, one entry per feed var."""
        cols = list(zip(*iterable))
        if len(cols) != len(self.feed_vars):
            raise ValueError(
                "sample width %d != #feed vars %d" % (len(cols), len(self.feed_vars))
            )
        out = {}
        for var, col in zip(self.feed_vars, cols):
            dtype = core_types.np_dtype(var.dtype)
            arrs = [np.asarray(c) for c in col]
            if var.lod_level and var.lod_level > 0:
                # ragged: pad to max length, emit companion length vector
                lens = np.array([a.shape[0] for a in arrs], dtype="int32")
                maxlen = int(lens.max()) if len(lens) else 0
                trailing = arrs[0].shape[1:] if arrs else ()
                padded = np.zeros((len(arrs), maxlen) + tuple(trailing), dtype=dtype)
                for i, a in enumerate(arrs):
                    padded[i, : a.shape[0]] = a
                out[var.name] = padded
                out[var.name + "_seq_len"] = lens
            else:
                batch = np.stack(arrs).astype(dtype)
                # reference reshapes flat samples to the declared shape
                want = var.shape
                if want is not None and len(batch.shape) != len(want):
                    concrete = [s if s != -1 else batch.shape[0] for s in want]
                    try:
                        batch = batch.reshape(concrete)
                    except ValueError:
                        pass
                out[var.name] = batch
        return out
