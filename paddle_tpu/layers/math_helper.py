"""Variable operator-overload sugar (reference: framework.py monkey patch +
layers/math_op_patch.py)."""
from __future__ import annotations

import numpy as np


def binary_op(x, other, op_type, reverse=False):
    from paddle_tpu.layer_helper import LayerHelper
    from paddle_tpu.layers import tensor as ltensor

    if isinstance(other, (int, float)):
        if op_type == "elementwise_add" and not reverse:
            return ltensor.scale(x, scale=1.0, bias=float(other))
        if op_type == "elementwise_sub":
            if reverse:
                return ltensor.scale(x, scale=-1.0, bias=float(other))
            return ltensor.scale(x, scale=1.0, bias=-float(other))
        if op_type == "elementwise_mul":
            return ltensor.scale(x, scale=float(other))
        if op_type == "elementwise_div" and not reverse:
            return ltensor.scale(x, scale=1.0 / float(other))
        # fall through: create a const var
        other = ltensor.fill_constant([1], x.dtype, float(other))
    a, b = (other, x) if reverse else (x, other)
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(a.dtype)
    helper.append_op(type=op_type, inputs={"X": [a], "Y": [b]}, outputs={"Out": [out]}, attrs={"axis": -1})
    return out
