"""Auto-generated-style unary layer wrappers.

Reference: python/paddle/fluid/layers/ops.py via layer_function_generator.py
— thin wrappers around registered activation/math ops.
"""
from __future__ import annotations

from paddle_tpu.layer_helper import LayerHelper

_UNARY = [
    "sigmoid",
    "logsigmoid",
    "exp",
    "tanh",
    "sqrt",
    "rsqrt",
    "abs",
    "ceil",
    "floor",
    "cos",
    "sin",
    "round",
    "reciprocal",
    "square",
    "softplus",
    "softsign",
    "log",
    "relu6",
    "elu",
    "swish",
    "hard_sigmoid",
    "hard_swish",
    "thresholded_relu",
    "stanh",
    "soft_relu",
    "brelu",
    "leaky_relu",
    "gelu",
    "sign",
]

__all__ = list(_UNARY)


def _make(op_type):
    def layer(x, *args, name=None, **kwargs):
        attrs = dict(kwargs)
        # positional alpha/threshold args map per-op; common case: first arg
        if args:
            keymap = {
                "leaky_relu": "alpha",
                "elu": "alpha",
                "relu6": "threshold",
                "swish": "beta",
                "thresholded_relu": "threshold",
                "soft_relu": "threshold",
            }
            attrs[keymap.get(op_type, "value")] = args[0]
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(type=op_type, inputs={"X": [x]}, outputs={"Out": [out]}, attrs=attrs)
        return out

    layer.__name__ = op_type
    return layer


for _name in _UNARY:
    globals()[_name] = _make(_name)
