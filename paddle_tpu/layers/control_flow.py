"""Control-flow layers: While, StaticRNN, cond, increment.

Reference: python/paddle/fluid/layers/control_flow.py — While:630,
StaticRNN:280, ConditionalBlock:1352, IfElse:1564.  The reference runs
sub-blocks through a nested Executor over scope chains; here the layer
classes compute the *loop-carried variable set* at build time and emit a
single structural op ("while" / "static_rnn" / "select_branch",
ops/control_flow_ops.py) that traces the sub-block into lax control flow.
"""
from __future__ import annotations

from typing import List, Optional

from paddle_tpu import framework, unique_name
from paddle_tpu.framework import Variable
from paddle_tpu.layer_helper import LayerHelper

__all__ = ["While", "StaticRNN", "DynamicRNN", "IfElse", "Switch", "cond",
           "increment", "create_array", "array_write", "array_read",
           "array_length", "lod_rank_table", "reorder_lod_tensor_by_rank"]


def increment(x, value=1.0, in_place=True):
    """reference: layers/control_flow.py increment."""
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="scale",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"scale": 1.0, "bias": float(value)},
    )
    return out


def _analyze_sub_block(sub_block, exclude_locals=()):
    """Return (carried, externals): names written by sub-block ops that
    live in an outer block (mutated loop state), and outer names read
    but never locally produced."""
    produced = set(exclude_locals)
    carried: List[str] = []
    externals: List[str] = []
    parent = sub_block.parent_block
    for op in sub_block.ops:
        for n in op.input_arg_names:
            if n in produced or n in carried or n in externals:
                continue
            if parent is not None and parent.has_var(n):
                externals.append(n)
        for n in op.output_arg_names:
            if parent is not None and parent.has_var(n) and n not in sub_block.vars:
                if n not in carried:
                    carried.append(n)
            produced.add(n)
    # a var both carried and external is loop state, not a constant input
    externals = [n for n in externals if n not in carried]
    return carried, externals


class While:
    """reference: layers/control_flow.py:630.

    ::

        i = layers.fill_constant(shape=[1], dtype='int64', value=0)
        cond = layers.less_than(i, limit)
        loop = layers.While(cond)
        with loop.block():
            ...  # ops mutating outer vars
            layers.less_than(i, limit, cond=cond)
    """

    def __init__(self, cond: Variable, is_test: bool = False, name: Optional[str] = None,
                 max_trip_count: Optional[int] = None):
        """``max_trip_count``: static trip bound; when given, the loop
        lowers to a differentiable scan (op ``bounded_while``) so
        ``append_backward`` can differentiate through it — the TPU-native
        grad-of-while (reference: controlflow/while_op.cc grad)."""
        self.cond_var = cond
        self.max_trip_count = max_trip_count
        self.helper = LayerHelper("while", name=name)

    class _BlockGuard:
        def __init__(self, w):
            self.w = w

        def __enter__(self):
            prog = framework.default_main_program()
            self.w.sub_block = prog._create_block()
            return self.w.sub_block

        def __exit__(self, exc_type, *a):
            if exc_type is not None:
                return False
            prog = framework.default_main_program()
            prog._rollback()
            w = self.w
            carried, externals = _analyze_sub_block(w.sub_block)
            if w.cond_var.name not in carried:
                carried.insert(0, w.cond_var.name)
            parent = prog.current_block()
            attrs = {
                "sub_block": w.sub_block,
                "carry_names": list(carried),
                "external_names": list(externals),
                "cond_name": w.cond_var.name,
            }
            op_type = "while"
            x_in = carried + externals
            if w.max_trip_count is not None:
                op_type = "bounded_while"
                attrs["max_trip_count"] = int(w.max_trip_count)
                # The loop writes its outputs over its own input names
                # (reference in-place Scope mutation).  The grad op later
                # re-reads X to recompute the forward, so it must see the
                # PRE-loop values — snapshot each carry into a fresh var
                # (the SSA-ification SURVEY.md §7 hard-part #3 calls for,
                # applied just where reverse-mode needs it).
                snap = []
                for n in carried:
                    v = parent._find_var_recursive(n)
                    sn = parent.create_var(
                        name=unique_name.generate(n + ".while_init"),
                        shape=v.shape,
                        dtype=v.dtype,
                        stop_gradient=v.stop_gradient,
                    )
                    parent.append_op(
                        type="assign",
                        inputs={"X": [n]},
                        outputs={"Out": [sn.name]},
                        attrs={},
                    )
                    snap.append(sn.name)
                x_in = snap + externals
            parent.append_op(
                type=op_type,
                inputs={"X": x_in},
                outputs={"Out": list(carried)},
                attrs=attrs,
            )
            return False

    def block(self):
        return While._BlockGuard(self)


def cond(pred: Variable, true_fn, false_fn):
    """Functional two-armed conditional (modern fluid layers.cond API;
    subsumes IfElse/ConditionalBlock for the common case)."""
    prog = framework.default_main_program()
    parent = prog.current_block()

    def build(fn):
        blk = prog._create_block()
        outs = fn()
        prog._rollback()
        if outs is None:
            outs = ()
        if isinstance(outs, Variable):
            outs = (outs,)
        return blk, [o.name for o in outs], list(outs)

    tblk, tnames, touts = build(true_fn)
    fblk, fnames, fouts = build(false_fn)
    if len(tnames) != len(fnames):
        raise ValueError("cond branches must return the same number of outputs")

    # externals = union of both branches' outer reads
    _, text = _analyze_sub_block(tblk)
    _, fext = _analyze_sub_block(fblk)
    externals = list(dict.fromkeys(text + fext))

    # false branch vars are renamed into the true branch's output names
    # so both arms bind the same out_names
    rename = dict(zip(fnames, tnames))
    for op in fblk.ops:
        for old, new in rename.items():
            op._rename_output(old, new)
            op._rename_input(old, new)

    out_vars = []
    for tv in touts:
        ov = parent.create_var(
            name=unique_name.generate(tv.name + ".cond_out"),
            shape=tv.shape,
            dtype=tv.dtype,
        )
        out_vars.append(ov)
    parent.append_op(
        type="select_branch",
        inputs={"Cond": [pred], "X": externals},
        outputs={"Out": [v.name for v in out_vars]},
        attrs={
            "true_block": tblk,
            "false_block": fblk,
            "out_names": tnames,
            "external_names": externals,
        },
    )
    return out_vars[0] if len(out_vars) == 1 else out_vars


class StaticRNN:
    """reference: layers/control_flow.py:280 — time-major recurrence.

    Inputs are [T, B, ...]; ``step_input`` slices one step, ``memory``
    declares loop state, ``step_output`` stacks per-step values.
    Lowered to one lax.scan (op static_rnn) — BPTT via scan transpose.
    """

    def __init__(self, name: Optional[str] = None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._x_pairs = []        # (outer var, placeholder)
        self._mem = []            # (placeholder, init outer var, updated name)
        self._outputs = []        # sub-block vars to stack
        self._built = False

    class _StepGuard:
        def __init__(self, rnn):
            self.rnn = rnn

        def __enter__(self):
            prog = framework.default_main_program()
            self.rnn.sub_block = prog._create_block()
            return self.rnn

        def __exit__(self, exc_type, *a):
            if exc_type is not None:
                return False
            framework.default_main_program()._rollback()
            self.rnn._complete()
            return False

    def step(self):
        return StaticRNN._StepGuard(self)

    # --- in-step API ---
    def step_input(self, x: Variable) -> Variable:
        ph = self.sub_block.create_var(
            name=unique_name.generate("rnn_step_in"),
            shape=x.shape[1:],
            dtype=x.dtype,
        )
        self._x_pairs.append((x, ph))
        return ph

    def memory(self, init: Optional[Variable] = None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=0) -> Variable:
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory needs init= or (shape=, batch_ref=)")
            # the init must live in the parent block (it is a loop input);
            # a step-input placeholder batch_ref maps back to its outer
            # time-major var (+1 on the batch dim index)
            parent = self.sub_block.parent_block
            ref_outer, dim_idx = None, ref_batch_dim_idx
            for outer, ph in self._x_pairs:
                if ph is batch_ref or ph.name == batch_ref.name:
                    ref_outer, dim_idx = outer, ref_batch_dim_idx + 1
                    break
            if ref_outer is None:
                ref_outer = batch_ref
            tail = list(shape[1:]) if shape and shape[0] in (-1, None) else list(shape)
            init = parent.create_var(
                name=unique_name.generate("rnn_mem_init"),
                shape=[-1] + tail,
                dtype="float32",
            )
            parent.append_op(
                type="fill_constant_batch_size_like",
                inputs={"Input": [ref_outer]},
                outputs={"Out": [init]},
                attrs={
                    "shape": [-1] + tail,
                    "value": float(init_value),
                    "dtype": "float32",
                    "input_dim_idx": dim_idx,
                    "output_dim_idx": init_batch_dim_idx,
                },
            )
        ph = self.sub_block.create_var(
            name=unique_name.generate("rnn_mem"),
            shape=init.shape,
            dtype=init.dtype,
        )
        self._mem.append([ph, init, None])
        return ph

    def update_memory(self, mem: Variable, new: Variable):
        for rec in self._mem:
            if rec[0] is mem or rec[0].name == mem.name:
                rec[2] = new.name
                return
        raise ValueError("update_memory: %r is not a declared memory" % mem.name)

    def step_output(self, o: Variable):
        self._outputs.append(o)

    def output(self, *outs):
        for o in outs:
            self.step_output(o)

    # --- completion ---
    def _complete(self):
        prog = framework.default_main_program()
        parent = prog.current_block()
        if any(rec[2] is None for rec in self._mem):
            raise ValueError("every memory needs update_memory before the step ends")

        locals_ = {ph.name for _, ph in self._x_pairs} | {rec[0].name for rec in self._mem}
        _, externals = _analyze_sub_block(self.sub_block, exclude_locals=locals_)
        externals = [n for n in externals if n not in locals_]

        x_outer = [x for x, _ in self._x_pairs]
        seq_len = x_outer[0].shape[0] if x_outer and x_outer[0].shape else None
        out_vars = []
        for o in self._outputs:
            ov = parent.create_var(
                name=unique_name.generate(o.name + ".rnn_out"),
                shape=(seq_len,) + tuple(o.shape or ()),
                dtype=o.dtype,
            )
            out_vars.append(ov)
        final_mems = []
        for ph, init, _ in self._mem:
            fv = parent.create_var(
                name=unique_name.generate(ph.name + ".final"),
                shape=init.shape,
                dtype=init.dtype,
            )
            final_mems.append(fv)

        parent.append_op(
            type="static_rnn",
            inputs={"X": [x.name for x in x_outer]
                    + [rec[1].name for rec in self._mem]
                    + externals},
            outputs={"Out": [v.name for v in out_vars] + [v.name for v in final_mems]},
            attrs={
                "sub_block": self.sub_block,
                "x_names": [ph.name for _, ph in self._x_pairs],
                "mem_names": [rec[0].name for rec in self._mem],
                "mem_out_names": [rec[2] for rec in self._mem],
                "out_names": [o.name for o in self._outputs],
                "external_names": externals,
            },
        )
        self._out_vars = out_vars
        self._built = True

    def __call__(self):
        if not self._built:
            raise RuntimeError("StaticRNN used before its step block completed")
        return self._out_vars[0] if len(self._out_vars) == 1 else self._out_vars


class DynamicRNN:
    """Variable-length recurrence (reference: layers/control_flow.py:1700).

    The reference walks LoD ragged batches with a shrinking batch; the
    TPU-native encoding is padded ``[B, T, ...]`` sequences plus a
    ``SeqLen`` vector (the framework's LoD shim, ops/sequence_ops.py), so
    DynamicRNN lowers to ONE lax.scan over the time axis with per-example
    masking (op ``dynamic_rnn``) — fully differentiable, fixed shapes.

    ::

        drnn = layers.DynamicRNN()
        with drnn.block():
            word = drnn.step_input(x, seq_len=lens)   # x: [B, T, D]
            prev = drnn.memory(shape=[H], value=0.0)
            hidden = layers.fc(layers.concat([word, prev], axis=1), H, act='tanh')
            drnn.update_memory(prev, hidden)
            drnn.output(hidden)
        out = drnn()    # [B, T, H]; padding steps are zero
    """

    def __init__(self, keep_memory: bool = False, name: Optional[str] = None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self._x_pairs = []      # (outer seq var [B,T,...], placeholder [B,...])
        self._statics = []      # (outer var, placeholder)
        self._mem = []          # [placeholder, init outer var, updated name]
        self._outputs = []
        self._seq_len = None
        self._built = False

    class _BlockGuard:
        def __init__(self, rnn):
            self.rnn = rnn

        def __enter__(self):
            prog = framework.default_main_program()
            self.rnn.sub_block = prog._create_block()
            return self.rnn

        def __exit__(self, exc_type, *a):
            if exc_type is not None:
                return False
            framework.default_main_program()._rollback()
            self.rnn._complete()
            return False

    def block(self):
        return DynamicRNN._BlockGuard(self)

    # --- in-step API ---
    def step_input(self, x: Variable, level: int = 0, seq_len: Optional[Variable] = None) -> Variable:
        """x: [B, T, ...] padded; ``seq_len``: [B] lengths (required on
        the first step_input — the reference reads lengths from the LoD)."""
        if seq_len is not None:
            self._seq_len = seq_len
        if self._seq_len is None:
            raise ValueError(
                "DynamicRNN.step_input needs seq_len= on its first call "
                "(padded+mask LoD encoding)"
            )
        ph = self.sub_block.create_var(
            name=unique_name.generate("drnn_step_in"),
            shape=(x.shape[0],) + tuple(x.shape[2:]),
            dtype=x.dtype,
        )
        self._x_pairs.append((x, ph))
        return ph

    def static_input(self, x: Variable) -> Variable:
        """Whole-sequence input visible unchanged at every step."""
        ph = self.sub_block.create_var(
            name=unique_name.generate("drnn_static_in"),
            shape=x.shape,
            dtype=x.dtype,
        )
        self._statics.append((x, ph))
        return ph

    def memory(self, init: Optional[Variable] = None, shape=None, value=0.0,
               need_reorder: bool = False, dtype: str = "float32") -> Variable:
        if init is None:
            if shape is None:
                raise ValueError("memory needs init= or shape=")
            if not self._x_pairs:
                raise ValueError("declare step_input before value-initialized memory")
            parent = self.sub_block.parent_block
            ref = self._x_pairs[0][0]
            tail = [int(s) for s in shape]
            init = parent.create_var(
                name=unique_name.generate("drnn_mem_init"),
                shape=[-1] + tail,
                dtype=dtype,
            )
            parent.append_op(
                type="fill_constant_batch_size_like",
                inputs={"Input": [ref]},
                outputs={"Out": [init]},
                attrs={"shape": [-1] + tail, "value": float(value),
                       "dtype": dtype, "input_dim_idx": 0, "output_dim_idx": 0},
            )
        ph = self.sub_block.create_var(
            name=unique_name.generate("drnn_mem"),
            shape=init.shape,
            dtype=init.dtype,
        )
        self._mem.append([ph, init, None])
        return ph

    def update_memory(self, mem: Variable, new: Variable):
        for rec in self._mem:
            if rec[0] is mem or rec[0].name == mem.name:
                rec[2] = new.name
                return
        raise ValueError("update_memory: %r is not a declared memory" % mem.name)

    def output(self, *outs):
        self._outputs.extend(outs)

    # --- completion ---
    def _complete(self):
        prog = framework.default_main_program()
        parent = prog.current_block()
        if any(rec[2] is None for rec in self._mem):
            raise ValueError("every memory needs update_memory before the block ends")
        if not self._x_pairs:
            raise ValueError("DynamicRNN needs at least one step_input")

        locals_ = (
            {ph.name for _, ph in self._x_pairs}
            | {ph.name for _, ph in self._statics}
            | {rec[0].name for rec in self._mem}
        )
        _, externals = _analyze_sub_block(self.sub_block, exclude_locals=locals_)
        externals = [n for n in externals if n not in locals_]

        x_outer = [x for x, _ in self._x_pairs]
        static_outer = [x for x, _ in self._statics]
        T = x_outer[0].shape[1] if len(x_outer[0].shape or ()) > 1 else None
        out_vars = []
        for o in self._outputs:
            shp = tuple(o.shape or ())
            ov = parent.create_var(
                name=unique_name.generate(o.name + ".drnn_out"),
                shape=(shp[0] if shp else -1, T) + tuple(shp[1:]),
                dtype=o.dtype,
            )
            out_vars.append(ov)
        final_mems = []
        for ph, init, _ in self._mem:
            fv = parent.create_var(
                name=unique_name.generate(ph.name + ".final"),
                shape=init.shape,
                dtype=init.dtype,
            )
            final_mems.append(fv)

        parent.append_op(
            type="dynamic_rnn",
            inputs={"X": [x.name for x in x_outer]
                    + [rec[1].name for rec in self._mem]
                    + [x.name for x in static_outer]
                    + externals,
                    "SeqLen": [self._seq_len.name]},
            outputs={"Out": [v.name for v in out_vars] + [v.name for v in final_mems]},
            attrs={
                "sub_block": self.sub_block,
                "x_names": [ph.name for _, ph in self._x_pairs],
                "mem_names": [rec[0].name for rec in self._mem],
                "mem_out_names": [rec[2] for rec in self._mem],
                "out_names": [o.name for o in self._outputs],
                "static_names": [ph.name for _, ph in self._statics] + externals,
            },
        )
        self._out_vars = out_vars
        self._final_mems = final_mems
        self._built = True

    def __call__(self):
        if not self._built:
            raise RuntimeError("DynamicRNN used before its block completed")
        return self._out_vars[0] if len(self._out_vars) == 1 else self._out_vars


def create_array(size, shape, dtype="float32", name=None):
    """LoDTensorArray analog: a pre-sized stacked tensor [size, *shape]
    (reference: layers/control_flow.py create_array over
    LOD_TENSOR_ARRAY; XLA needs the static bound up front)."""
    from paddle_tpu.layers import tensor as ltensor

    return ltensor.fill_constant([int(size)] + list(shape), dtype, 0.0)


def array_write(x, i, array):
    """reference: layers/control_flow.py array_write.

    Writes OVER the array var (Out == Array), matching the reference's
    in-place LoDTensorArray mutation — critical inside a While sub-block,
    where only vars the sub-block *writes* become loop-carried state
    (``_analyze_sub_block``); an SSA fresh-var output would silently drop
    every write on the next iteration."""
    helper = LayerHelper("array_write")
    helper.append_op(
        type="write_to_array",
        inputs={"Array": [array], "I": [i], "X": [x]},
        outputs={"Out": [array]},
        attrs={},
    )
    return array


def array_read(array, i):
    """reference: layers/control_flow.py array_read."""
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op(
        type="read_from_array",
        inputs={"X": [array], "I": [i]},
        outputs={"Out": [out]},
        attrs={},
    )
    return out


def array_length(array):
    """Length of the array: the STATIC allocated capacity (create_array
    size), not a written-element count — the padded-static shim's
    divergence from the reference's growing LoDTensorArray.  Track a
    separate counter var if the loop writes fewer slots."""
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="lod_array_length", inputs={"X": [array]},
                     outputs={"Out": [out]}, attrs={})
    return out


class IfElse:
    """reference: layers/control_flow.py:1564 — per-example two-way
    branch: true_block/false_block see the rows selected by the
    condition; outputs merge back in original order.

    TPU-native: both blocks run on the FULL batch (SPMD-friendly, no
    dynamic shapes) and jnp.where merges per row — semantically the
    reference's split+merge for elementwise-batch computations.

    GRADIENT CAVEAT (the classic where-grad gotcha): because the
    unselected branch still executes on every row, a branch whose vjp is
    non-finite on unselected rows (sqrt/log/div of invalid inputs)
    poisons the gradient (0 * NaN = NaN).  Guard the branch INPUT, not
    just its output: ``safe = layers.where(cond, x, ones_like(x))``
    inside the branch.
    """

    def __init__(self, cond: Variable, name: Optional[str] = None):
        self._cond = cond
        self._true_outs: List[Variable] = []
        self._false_outs: List[Variable] = []
        self._in_true = None

    class _Branch:
        def __init__(self, parent, is_true):
            self.parent, self.is_true = parent, is_true

        def __enter__(self):
            self.parent._in_true = self.is_true
            return self

        def __exit__(self, *exc):
            self.parent._in_true = None
            return False

    def true_block(self):
        return IfElse._Branch(self, True)

    def false_block(self):
        return IfElse._Branch(self, False)

    def input(self, x: Variable) -> Variable:
        # full-batch pass-through (the reference slices selected rows;
        # here masking happens at merge)
        return x

    def output(self, *outs):
        if self._in_true is None:
            raise RuntimeError("IfElse.output called outside a branch block")
        (self._true_outs if self._in_true else self._false_outs).extend(outs)

    def __call__(self):
        if len(self._true_outs) != len(self._false_outs):
            raise ValueError("IfElse branches produced different output counts")
        from paddle_tpu.layers import tensor as ltensor

        merged = [
            ltensor.where(self._cond, t, f)
            for t, f in zip(self._true_outs, self._false_outs)
        ]
        return merged[0] if len(merged) == 1 else merged


class Switch:
    """reference: layers/control_flow.py Switch — sequential
    case/default assignment, lowered to nested where-selects."""

    def __init__(self, name: Optional[str] = None):
        self._cases = []  # (cond_var or None, fn-scope marker)
        self._pending = None

    class _Case:
        def __init__(self, sw, cond):
            self.sw, self.cond = sw, cond

        def __enter__(self):
            self.sw._pending = (self.cond, [])
            return self

        def __exit__(self, *exc):
            self.sw._cases.append(self.sw._pending)
            self.sw._pending = None
            return False

    def case(self, cond: Variable):
        return Switch._Case(self, cond)

    def default(self):
        return Switch._Case(self, None)

    def assign(self, var: Variable):
        """Record this branch's value (call inside a case block)."""
        if self._pending is None:
            raise RuntimeError("Switch.assign outside a case block")
        self._pending[1].append(var)

    def merge(self):
        """Fold cases: first true condition wins, else default."""
        from paddle_tpu.layers import tensor as ltensor

        default = None
        conds = []
        for cond, vals in self._cases:
            if len(vals) != 1:
                raise ValueError(
                    "each Switch case needs exactly one assign (got %d)" % len(vals)
                )
            if cond is None:
                default = vals[0]
            else:
                conds.append((cond, vals[0]))
        if default is None:
            raise ValueError("Switch needs a default case")
        out = default
        for cond, val in reversed(conds):
            out = ltensor.where(cond, val, out)
        return out


def lod_rank_table(x, level=0, seq_len=None):
    """Rank table sorted by sequence length descending (reference:
    layers/control_flow.py lod_rank_table + lod_rank_table.cc).

    On the padded encoding the table is built from the companion length
    vector: for a ``data(lod_level>=1)`` var the ``<name>_seq_len``
    (level 0) or ``<name>_inner_len`` (level 1) var is found
    automatically; pass ``seq_len`` explicitly otherwise.  Returns the
    index var (sorted original positions); its ``.lengths`` attribute
    holds the sorted-lengths var."""
    helper = LayerHelper("lod_rank_table")
    if seq_len is None:
        suffix = "_seq_len" if level == 0 else "_inner_len"
        block = helper.main_program.current_block()
        name = getattr(x, "name", str(x)) + suffix
        seq_len = block._find_var_recursive(name)
        if seq_len is None:
            raise ValueError(
                "lod_rank_table: no companion %r length var; pass seq_len" % name
            )
    index = helper.create_variable_for_type_inference("int32", stop_gradient=True)
    lengths = helper.create_variable_for_type_inference("int32", stop_gradient=True)
    helper.append_op(
        type="lod_rank_table", inputs={"X": [seq_len]},
        outputs={"Index": [index], "Length": [lengths]},
        attrs={"level": int(level)},
    )
    index.lengths = lengths
    return index


def reorder_lod_tensor_by_rank(x, rank_table):
    """Gather x's batch rows into rank-table order (reference:
    layers/control_flow.py reorder_lod_tensor_by_rank +
    reorder_lod_tensor_by_rank_op.cc)."""
    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="reorder_lod_tensor_by_rank",
        inputs={"X": [x], "RankTable": [rank_table]},
        outputs={"Out": [out]}, attrs={},
    )
    return out
