"""Detection layers (reference: python/paddle/fluid/layers/detection.py —
prior_box, box_coder, iou_similarity, yolo_box, multiclass_nms...)."""
from __future__ import annotations

from paddle_tpu.layer_helper import LayerHelper

__all__ = ["prior_box", "box_coder", "iou_similarity", "yolo_box", "multiclass_nms",
           "anchor_generator", "box_clip", "roi_align", "roi_pool",
           "bipartite_match", "target_assign"]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={
            "min_sizes": list(min_sizes),
            "max_sizes": list(max_sizes or []),
            "aspect_ratios": list(aspect_ratios),
            "variances": list(variance),
            "flip": flip,
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
        },
    )
    boxes.stop_gradient = var.stop_gradient = True
    return boxes, var


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, name=None):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference(target_box.dtype)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        type="box_coder",
        inputs=inputs,
        outputs={"OutputBox": [out]},
        attrs={"code_type": code_type, "box_normalized": box_normalized},
    )
    return out


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="iou_similarity",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"box_normalized": box_normalized},
    )
    return out


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, name=None):
    helper = LayerHelper("yolo_box", name=name)
    boxes = helper.create_variable_for_type_inference(x.dtype)
    scores = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="yolo_box",
        inputs={"X": [x], "ImgSize": [img_size]},
        outputs={"Boxes": [boxes], "Scores": [scores]},
        attrs={
            "anchors": list(anchors),
            "class_num": class_num,
            "conf_thresh": conf_thresh,
            "downsample_ratio": downsample_ratio,
        },
    )
    return boxes, scores


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=64,
                   keep_top_k=16, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    """Static-shape NMS: [N, keep_top_k, 6], label -1 = padding (see
    ops/detection_ops.py)."""
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    helper.append_op(
        type="multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out]},
        attrs={
            "score_threshold": score_threshold,
            "nms_top_k": nms_top_k,
            "keep_top_k": keep_top_k,
            "nms_threshold": nms_threshold,
            "normalized": normalized,
        },
    )
    out.stop_gradient = True
    return out


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    """reference: layers/detection.py anchor_generator."""
    helper = LayerHelper("anchor_generator", name=name)
    anchors = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="anchor_generator", inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [variances]},
        attrs={"anchor_sizes": list(anchor_sizes or [64.0]),
               "aspect_ratios": list(aspect_ratios or [1.0]),
               "variances": list(variance),
               "stride": list(stride or [16.0, 16.0]),
               "offset": offset},
    )
    return anchors, variances


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="box_clip", inputs={"Input": [input], "ImInfo": [im_info]},
                     outputs={"Output": [out]}, attrs={})
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
              sampling_ratio=-1, batch_index=None, name=None):
    helper = LayerHelper("roi_align", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input], "ROIs": [rois]}
    if batch_index is not None:
        ins["BatchIndex"] = [batch_index]
    helper.append_op(type="roi_align", inputs=ins, outputs={"Out": [out]},
                     attrs={"pooled_height": pooled_height, "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale, "sampling_ratio": sampling_ratio})
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
             batch_index=None, name=None):
    helper = LayerHelper("roi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input], "ROIs": [rois]}
    if batch_index is not None:
        ins["BatchIndex"] = [batch_index]
    helper.append_op(type="roi_pool", inputs=ins, outputs={"Out": [out]},
                     attrs={"pooled_height": pooled_height, "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale})
    return out


def bipartite_match(dist_matrix, match_type="bipartite", dist_threshold=0.5, name=None):
    helper = LayerHelper("bipartite_match", name=name)
    idx = helper.create_variable_for_type_inference("int32")
    dist = helper.create_variable_for_type_inference(dist_matrix.dtype)
    helper.append_op(
        type="bipartite_match", inputs={"DistMat": [dist_matrix]},
        outputs={"ColToRowMatchIndices": [idx], "ColToRowMatchDist": [dist]},
        attrs={"match_type": match_type, "dist_threshold": dist_threshold},
    )
    return idx, dist


def target_assign(input, matched_indices, mismatch_value=0, name=None):
    helper = LayerHelper("target_assign", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    w = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="target_assign",
        inputs={"X": [input], "MatchIndices": [matched_indices]},
        outputs={"Out": [out], "OutWeight": [w]},
        attrs={"mismatch_value": mismatch_value},
    )
    return out, w
