"""Detection layers (reference: python/paddle/fluid/layers/detection.py —
prior_box, box_coder, iou_similarity, yolo_box, multiclass_nms,
yolov3_loss, ssd_loss, mine_hard_examples, density_prior_box...).

Ragged gt convention: the reference feeds ground truth as LoD tensors;
here gt boxes/labels are padded to [N, B, ...] with zero-area boxes
(w/h <= 1e-6) marking padding rows — the framework-wide padded+mask
convention (SURVEY.md LoD mapping)."""
from __future__ import annotations

from paddle_tpu.layer_helper import LayerHelper

__all__ = ["prior_box", "box_coder", "iou_similarity", "yolo_box", "multiclass_nms",
           "anchor_generator", "box_clip", "roi_align", "roi_pool",
           "bipartite_match", "target_assign", "yolov3_loss", "ssd_loss",
           "mine_hard_examples", "density_prior_box", "sigmoid_focal_loss",
           "multi_box_head", "detection_output", "rpn_target_assign",
           "generate_proposals", "detection_map",
           "polygon_box_transform", "distribute_fpn_proposals",
           "collect_fpn_proposals", "box_decoder_and_assign",
           "generate_proposal_labels", "generate_mask_labels",
           "retinanet_target_assign", "retinanet_detection_output",
           "roi_perspective_transform"]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={
            "min_sizes": list(min_sizes),
            "max_sizes": list(max_sizes or []),
            "aspect_ratios": list(aspect_ratios),
            "variances": list(variance),
            "flip": flip,
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
        },
    )
    boxes.stop_gradient = var.stop_gradient = True
    return boxes, var


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, name=None):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference(target_box.dtype)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        type="box_coder",
        inputs=inputs,
        outputs={"OutputBox": [out]},
        attrs={"code_type": code_type, "box_normalized": box_normalized},
    )
    return out


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="iou_similarity",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"box_normalized": box_normalized},
    )
    return out


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, name=None):
    helper = LayerHelper("yolo_box", name=name)
    boxes = helper.create_variable_for_type_inference(x.dtype)
    scores = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="yolo_box",
        inputs={"X": [x], "ImgSize": [img_size]},
        outputs={"Boxes": [boxes], "Scores": [scores]},
        attrs={
            "anchors": list(anchors),
            "class_num": class_num,
            "conf_thresh": conf_thresh,
            "downsample_ratio": downsample_ratio,
        },
    )
    return boxes, scores


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=64,
                   keep_top_k=16, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    """Static-shape NMS: [N, keep_top_k, 6], label -1 = padding (see
    ops/detection_ops.py)."""
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    helper.append_op(
        type="multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out]},
        attrs={
            "score_threshold": score_threshold,
            "nms_top_k": nms_top_k,
            "keep_top_k": keep_top_k,
            "nms_threshold": nms_threshold,
            "normalized": normalized,
        },
    )
    out.stop_gradient = True
    return out


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    """reference: layers/detection.py anchor_generator."""
    helper = LayerHelper("anchor_generator", name=name)
    anchors = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="anchor_generator", inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [variances]},
        attrs={"anchor_sizes": list(anchor_sizes or [64.0]),
               "aspect_ratios": list(aspect_ratios or [1.0]),
               "variances": list(variance),
               "stride": list(stride or [16.0, 16.0]),
               "offset": offset},
    )
    return anchors, variances


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="box_clip", inputs={"Input": [input], "ImInfo": [im_info]},
                     outputs={"Output": [out]}, attrs={})
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
              sampling_ratio=-1, batch_index=None, name=None):
    helper = LayerHelper("roi_align", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input], "ROIs": [rois]}
    if batch_index is not None:
        ins["BatchIndex"] = [batch_index]
    helper.append_op(type="roi_align", inputs=ins, outputs={"Out": [out]},
                     attrs={"pooled_height": pooled_height, "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale, "sampling_ratio": sampling_ratio})
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
             batch_index=None, name=None):
    helper = LayerHelper("roi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input], "ROIs": [rois]}
    if batch_index is not None:
        ins["BatchIndex"] = [batch_index]
    helper.append_op(type="roi_pool", inputs=ins, outputs={"Out": [out]},
                     attrs={"pooled_height": pooled_height, "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale})
    return out


def bipartite_match(dist_matrix, match_type="bipartite", dist_threshold=0.5, name=None):
    helper = LayerHelper("bipartite_match", name=name)
    idx = helper.create_variable_for_type_inference("int32")
    dist = helper.create_variable_for_type_inference(dist_matrix.dtype)
    helper.append_op(
        type="bipartite_match", inputs={"DistMat": [dist_matrix]},
        outputs={"ColToRowMatchIndices": [idx], "ColToRowMatchDist": [dist]},
        attrs={"match_type": match_type, "dist_threshold": dist_threshold},
    )
    return idx, dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    """reference: layers/detection.py target_assign — NegIndices is the
    [N, M] 0/1 mask (padded analog of the reference's LoD index list)."""
    helper = LayerHelper("target_assign", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    w = helper.create_variable_for_type_inference("float32")
    ins = {"X": [input], "MatchIndices": [matched_indices]}
    if negative_indices is not None:
        ins["NegIndices"] = [negative_indices]
    helper.append_op(
        type="target_assign", inputs=ins,
        outputs={"Out": [out], "OutWeight": [w]},
        attrs={"mismatch_value": mismatch_value},
    )
    out.stop_gradient = True
    w.stop_gradient = True
    return out, w


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    """YOLOv3 training loss (reference: layers/detection.py yolov3_loss +
    operators/detection/yolov3_loss_op.cc).  Returns the per-image loss
    [N].  gt_box [N, B, 4] normalized center-form; padding rows are
    zero-area boxes."""
    helper = LayerHelper("yolov3_loss", name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    obj_mask = helper.create_variable_for_type_inference(x.dtype)
    gt_match = helper.create_variable_for_type_inference("int32")
    inputs = {"X": [x], "GTBox": [gt_box], "GTLabel": [gt_label]}
    if gt_score is not None:
        inputs["GTScore"] = [gt_score]
    helper.append_op(
        type="yolov3_loss",
        inputs=inputs,
        outputs={"Loss": [loss], "ObjectnessMask": [obj_mask],
                 "GTMatchMask": [gt_match]},
        attrs={
            "anchors": list(anchors),
            "anchor_mask": list(anchor_mask),
            "class_num": class_num,
            "ignore_thresh": ignore_thresh,
            "downsample_ratio": downsample_ratio,
            "use_label_smooth": use_label_smooth,
        },
    )
    obj_mask.stop_gradient = True
    gt_match.stop_gradient = True
    return loss


def mine_hard_examples(cls_loss, match_indices, match_dist,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5,
                       mining_type="max_negative", sample_size=None,
                       name=None):
    """reference: ssd_loss's mine_hard_examples op append
    (layers/detection.py:1408).  NegIndices is a [N, M] 0/1 mask (padded
    analog of the reference's LoD index list)."""
    helper = LayerHelper("mine_hard_examples", name=name)
    neg = helper.create_variable_for_type_inference("int32")
    updated = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="mine_hard_examples",
        inputs={"ClsLoss": [cls_loss], "MatchIndices": [match_indices],
                "MatchDist": [match_dist]},
        outputs={"NegIndices": [neg], "UpdatedMatchIndices": [updated]},
        attrs={"neg_pos_ratio": neg_pos_ratio,
               "neg_dist_threshold": neg_dist_threshold,
               "mining_type": mining_type,
               "sample_size": sample_size or 0},
    )
    neg.stop_gradient = True
    updated.stop_gradient = True
    return neg, updated


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None):
    """SSD multibox loss (reference: layers/detection.py:1246 ssd_loss) —
    the same 5-step composition (match -> conf loss -> hard-negative
    mining -> target assign -> weighted loss) over the padded gt
    convention: gt_box [N, B, 4], gt_label [N, B] (or [N, B, 1]) with
    zero-area boxes marking padding.

    location [N, P, 4]; confidence [N, P, C]; prior_box [P, 4].
    Returns the weighted loss [N*P, 1] like the reference."""
    from paddle_tpu.layers import nn, tensor

    if mining_type != "max_negative":
        raise ValueError("Only support mining_type == max_negative now.")
    N, P, C = confidence.shape

    # 1. match priors to gt: IoU [N, B, P] -> dist [N, P_rows, B_cols]
    iou = iou_similarity(gt_box, prior_box)
    dist = tensor.transpose(iou, [0, 2, 1])
    matched_indices, matched_dist = bipartite_match(
        dist, match_type, overlap_threshold
    )

    # 2. first-pass conf loss for mining
    if len(gt_label.shape) == 2:
        gt_label = tensor.reshape(gt_label, shape=[0, -1, 1])
    gt_label.stop_gradient = True
    target_label, _ = target_assign(
        gt_label, matched_indices, mismatch_value=background_label
    )
    conf2d = tensor.reshape(confidence, shape=[-1, C])
    tl2d = tensor.reshape(tensor.cast(target_label, "int64"), shape=[-1, 1])
    tl2d.stop_gradient = True
    conf_loss = nn.softmax_with_cross_entropy(conf2d, tl2d)
    conf_loss_nm = tensor.reshape(conf_loss, shape=[-1, P])
    conf_loss_nm.stop_gradient = True

    # 3. mine hard negatives
    neg_mask, updated_match = mine_hard_examples(
        conf_loss_nm, matched_indices, matched_dist,
        neg_pos_ratio=neg_pos_ratio, neg_dist_threshold=neg_overlap,
        mining_type=mining_type, sample_size=sample_size,
    )

    # 4. regression / classification targets
    encoded_bbox = box_coder(
        prior_box=prior_box, prior_box_var=prior_box_var,
        target_box=gt_box, code_type="encode_center_size",
    )  # [N, B, P, 4]
    target_bbox, target_loc_weight = target_assign(
        encoded_bbox, updated_match, mismatch_value=background_label
    )
    target_label2, target_conf_weight = target_assign(
        gt_label, updated_match, negative_indices=neg_mask,
        mismatch_value=background_label,
    )

    # 5. weighted losses
    tl2 = tensor.reshape(tensor.cast(target_label2, "int64"), shape=[-1, 1])
    tl2.stop_gradient = True
    conf_loss = nn.softmax_with_cross_entropy(conf2d, tl2)
    conf_w = tensor.reshape(target_conf_weight, shape=[-1, 1])
    conf_loss = tensor.elementwise_mul(conf_loss, conf_w)

    loc2d = tensor.reshape(location, shape=[-1, 4])
    tb2d = tensor.reshape(target_bbox, shape=[-1, 4])
    tb2d.stop_gradient = True
    loc_loss = nn.smooth_l1(loc2d, tb2d)
    loc_w = tensor.reshape(target_loc_weight, shape=[-1, 1])
    loc_loss = tensor.elementwise_mul(loc_loss, loc_w)

    loss = tensor.elementwise_add(
        tensor.scale(conf_loss, scale=conf_loss_weight),
        tensor.scale(loc_loss, scale=loc_loss_weight),
    )
    loss = tensor.reshape(loss, shape=[-1, P])
    loss = tensor.reduce_sum(loss, dim=1, keep_dim=True)
    if normalize:
        normalizer = tensor.reduce_sum(target_loc_weight)
        loss = tensor.elementwise_div(loss, normalizer)
    return tensor.reshape(loss, shape=[-1, 1])


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    """reference: layers/detection.py:1608 density_prior_box."""
    from paddle_tpu.layers import tensor

    helper = LayerHelper("density_prior_box", name=name)
    boxes = helper.create_variable_for_type_inference("float32")
    var = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="density_prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={
            "densities": list(densities or []),
            "fixed_sizes": list(fixed_sizes or []),
            "fixed_ratios": list(fixed_ratios or [1.0]),
            "variances": list(variance),
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
        },
    )
    boxes.stop_gradient = var.stop_gradient = True
    if flatten_to_2d:
        boxes = tensor.reshape(boxes, shape=[-1, 4])
        var = tensor.reshape(var, shape=[-1, 4])
    return boxes, var


def sigmoid_focal_loss(x, label, fg_num, gamma=2, alpha=0.25):
    """reference: layers/detection.py:372 sigmoid_focal_loss."""
    helper = LayerHelper("sigmoid_focal_loss")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sigmoid_focal_loss",
        inputs={"X": [x], "Label": [label], "FgNum": [fg_num]},
        outputs={"Out": [out]},
        attrs={"gamma": gamma, "alpha": alpha},
    )
    return out


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None):
    """SSD detection head (reference: layers/detection.py:1737
    multi_box_head): per feature map, a conv predicting [loc, conf] per
    prior + the matching prior boxes; results concatenated over maps.

    Returns (mbox_locs [N, P, 4], mbox_confs [N, P, C],
    prior_boxes [P, 4], variances [P, 4])."""
    from paddle_tpu.layers import nn, tensor

    n_maps = len(inputs)
    if min_sizes is None:
        # the reference's ratio interpolation (detection.py:1898)
        min_sizes, max_sizes = [], []
        if min_ratio is None or max_ratio is None:
            raise ValueError("either min_sizes/max_sizes or min_ratio/max_ratio")
        step = int((max_ratio - min_ratio) / (n_maps - 2)) if n_maps > 2 else 0
        min_sizes = [base_size * 0.1]
        max_sizes = [base_size * 0.2]
        for ratio in range(min_ratio, max_ratio + 1, max(step, 1)):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = min_sizes[:n_maps]
        max_sizes = max_sizes[:n_maps]

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, feat in enumerate(inputs):
        ms = min_sizes[i]
        mx = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i] if isinstance(aspect_ratios[0], (list, tuple)) else aspect_ratios
        st = steps[i] if steps else (step_w[i] if step_w else 0.0,
                                     step_h[i] if step_h else 0.0)
        if not isinstance(st, (list, tuple)):
            st = (st, st)
        box, var = prior_box(
            feat, image, [ms] if not isinstance(ms, (list, tuple)) else ms,
            [mx] if mx and not isinstance(mx, (list, tuple)) else mx,
            ar, variance, flip, clip, st, offset,
        )
        box = tensor.reshape(box, shape=[-1, 4])
        var = tensor.reshape(var, shape=[-1, 4])
        # prior count per cell is derived from the prior_box output size
        # at compile time: total / (H*W)
        hw = feat.shape[2] * feat.shape[3]
        num_priors = box.shape[0] // hw

        loc = nn.conv2d(feat, num_filters=num_priors * 4,
                        filter_size=kernel_size, padding=pad, stride=stride)
        conf = nn.conv2d(feat, num_filters=num_priors * num_classes,
                         filter_size=kernel_size, padding=pad, stride=stride)
        # [N, A*4, H, W] -> [N, H, W, A*4] -> [N, H*W*A, 4]
        loc = tensor.transpose(loc, [0, 2, 3, 1])
        conf = tensor.transpose(conf, [0, 2, 3, 1])
        locs.append(tensor.reshape(loc, shape=[0, -1, 4]))
        confs.append(tensor.reshape(conf, shape=[0, -1, num_classes]))
        boxes_all.append(box)
        vars_all.append(var)

    mbox_locs = tensor.concat(locs, axis=1)
    mbox_confs = tensor.concat(confs, axis=1)
    prior_boxes = tensor.concat(boxes_all, axis=0)
    box_vars = tensor.concat(vars_all, axis=0)
    prior_boxes.stop_gradient = box_vars.stop_gradient = True
    return mbox_locs, mbox_confs, prior_boxes, box_vars


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """reference: layers/detection.py:440 detection_output — decode
    loc vs priors then multiclass NMS.  Returns [N, keep_top_k, 6]
    padded with label -1 (static-shape analog of the LoD output)."""
    from paddle_tpu.layers import nn, tensor

    decoded = box_coder(
        prior_box=prior_box, prior_box_var=prior_box_var, target_box=loc,
        code_type="decode_center_size",
    )
    scores = nn.softmax(scores)
    scores = tensor.transpose(scores, [0, 2, 1])  # [N, C, P]
    # decoded SSD boxes are in normalized [0,1] coordinates — the op's
    # normalized attr must stay true or the +1-pixel IoU convention
    # inflates overlap and suppresses distinct objects (the reference
    # leaves the attr at its default true here)
    return multiclass_nms(
        decoded, scores, score_threshold=score_threshold,
        nms_top_k=nms_top_k, keep_top_k=keep_top_k,
        nms_threshold=nms_threshold, normalized=True,
    )


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """reference: layers/detection.py:221 rpn_target_assign.

    Static-shape variant: instead of gathering sampled anchors into
    compact LoD tensors, returns full-anchor tensors + weights —
    (predicted_scores [N, A, 1], predicted_location [N, A, 4],
    target_label [N, A, 1] (-1 = unsampled), target_bbox [N, A, 4],
    bbox_inside_weight [N, A, 4], score_weight [N, A, 1]).  Sampling is
    the deterministic use_random=False reference path; multiply the
    score loss by score_weight and the loc loss by bbox_inside_weight to
    reproduce the reference objective."""
    from paddle_tpu.layers import tensor

    helper = LayerHelper("rpn_target_assign")
    label = helper.create_variable_for_type_inference("int32")
    tgt_bbox = helper.create_variable_for_type_inference("float32")
    loc_w = helper.create_variable_for_type_inference("float32")
    score_w = helper.create_variable_for_type_inference("float32")
    ins = {"Anchor": [anchor_box], "GtBoxes": [gt_boxes]}
    if im_info is not None:
        ins["ImInfo"] = [im_info]
    helper.append_op(
        type="rpn_target_assign", inputs=ins,
        outputs={"TargetLabel": [label], "TargetBBox": [tgt_bbox],
                 "LocWeight": [loc_w], "ScoreWeight": [score_w]},
        attrs={"rpn_batch_size_per_im": rpn_batch_size_per_im,
               "rpn_straddle_thresh": rpn_straddle_thresh,
               "rpn_fg_fraction": rpn_fg_fraction,
               "rpn_positive_overlap": rpn_positive_overlap,
               "rpn_negative_overlap": rpn_negative_overlap},
    )
    for v in (label, tgt_bbox, loc_w, score_w):
        v.stop_gradient = True
    A = anchor_box.shape[0]
    label3 = tensor.reshape(label, shape=[0, A, 1])
    locw3 = tensor.reshape(loc_w, shape=[0, A, 1])
    scw3 = tensor.reshape(score_w, shape=[0, A, 1])
    bbox_inside_weight = tensor.expand(locw3, expand_times=[1, 1, 4])
    return (cls_logits, bbox_pred, label3, tgt_bbox,
            bbox_inside_weight, scw3)


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None):
    """reference: layers/detection.py:2410 generate_proposals.  Returns
    (rpn_rois [N, post_nms_top_n, 4], rpn_roi_probs [N, post_nms_top_n, 1])
    padded with zero boxes / -1 scores (static analog of the LoD out)."""
    helper = LayerHelper("generate_proposals", name=name)
    rois = helper.create_variable_for_type_inference("float32")
    probs = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="generate_proposals",
        inputs={"Scores": [scores], "BboxDeltas": [bbox_deltas],
                "ImInfo": [im_info], "Anchors": [anchors],
                "Variances": [variances]},
        outputs={"RpnRois": [rois], "RpnRoiProbs": [probs]},
        attrs={"pre_nms_topN": pre_nms_top_n, "post_nms_topN": post_nms_top_n,
               "nms_thresh": nms_thresh, "min_size": min_size, "eta": eta},
    )
    rois.stop_gradient = True
    probs.stop_gradient = True
    return rois, probs


def detection_map(detect_res, label, class_num, gt_box=None,
                  background_label=0, overlap_threshold=0.5,
                  evaluate_difficult=True, ap_version="integral"):
    """reference: layers/detection.py:968 detection_map — batch mAP.
    Padded convention: detect_res [N, K, 6]; label [N, B] + gt_box
    [N, B, 4] (the reference packs gt into one LoD tensor; here they are
    separate padded tensors, so ``gt_box`` is required).
    ``evaluate_difficult`` is accepted for signature parity but the
    padded gt carries no difficult flag — every valid gt is evaluated.
    Streaming across batches lives in metrics.DetectionMAP."""
    if gt_box is None:
        raise ValueError(
            "detection_map needs gt_box: the reference packs [label, ...,"
            " box] into one LoD tensor; the padded convention passes"
            " labels [N, B] and boxes [N, B, 4] separately"
        )
    helper = LayerHelper("detection_map")
    m = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="detection_map",
        inputs={"DetectRes": [detect_res], "Label": [label],
                "GtBox": [gt_box]},
        outputs={"MAP": [m]},
        attrs={"overlap_threshold": overlap_threshold,
               "class_num": class_num,
               "background_label": background_label,
               "ap_type": ap_version},
    )
    m.stop_gradient = True
    return m


# ---------------------------------------------------------------------------
# FPN / Mask R-CNN / RetinaNet tail (reference: layers/detection.py
# distribute_fpn_proposals, collect_fpn_proposals, box_decoder_and_assign,
# generate_proposal_labels:2148, generate_mask_labels,
# retinanet_target_assign, retinanet_detection_output,
# polygon_box_transform, roi_perspective_transform)
# ---------------------------------------------------------------------------
def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="polygon_box_transform", inputs={"Input": [input]},
                     outputs={"Output": [out]}, attrs={})
    return out


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, name=None, rois_num=None):
    """Returns ([rois_level_min..max], restore_index); each level tensor
    is the full padded shape with its real count packed to the top (the
    ``.level_counts`` attr var holds the counts)."""
    helper = LayerHelper("distribute_fpn_proposals", name=name)
    n = max_level - min_level + 1
    outs = {"MultiFpnRois%d" % i: [helper.create_variable_for_type_inference(
        fpn_rois.dtype)] for i in range(n)}
    restore = helper.create_variable_for_type_inference("int32")
    counts = helper.create_variable_for_type_inference("int32")
    outs["RestoreIndex"] = [restore]
    outs["LevelCounts"] = [counts]
    ins = {"FpnRois": [fpn_rois]}
    if rois_num is not None:
        ins["RoisNum"] = [rois_num]
    helper.append_op(
        type="distribute_fpn_proposals", inputs=ins, outputs=outs,
        attrs={"min_level": int(min_level), "max_level": int(max_level),
               "refer_level": int(refer_level), "refer_scale": int(refer_scale)},
    )
    multi = [outs["MultiFpnRois%d" % i][0] for i in range(n)]
    for v in multi:
        v.stop_gradient = True
        v.level_counts = counts
    restore.stop_gradient = True
    return multi, restore


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, name=None):
    helper = LayerHelper("collect_fpn_proposals", name=name)
    out = helper.create_variable_for_type_inference(multi_rois[0].dtype)
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="collect_fpn_proposals",
        inputs={"MultiLevelRois": list(multi_rois),
                "MultiLevelScores": list(multi_scores)},
        outputs={"FpnRois": [out], "RoisNum": [num]},
        attrs={"post_nms_topN": int(post_nms_top_n)},
    )
    out.stop_gradient = True
    out.rois_num = num
    return out


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip, name=None):
    helper = LayerHelper("box_decoder_and_assign", name=name)
    decoded = helper.create_variable_for_type_inference(prior_box.dtype)
    assigned = helper.create_variable_for_type_inference(prior_box.dtype)
    helper.append_op(
        type="box_decoder_and_assign",
        inputs={"PriorBox": [prior_box], "PriorBoxVar": [prior_box_var],
                "TargetBox": [target_box], "BoxScore": [box_score]},
        outputs={"DecodeBox": [decoded], "OutputAssignBox": [assigned]},
        attrs={"box_clip": float(box_clip)},
    )
    return decoded, assigned


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256, fg_fraction=0.25,
                             fg_thresh=0.25, bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=[0.1, 0.1, 0.2, 0.2],
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False):
    """reference: layers/detection.py:2148.  Single-image static-shape
    sampler; returns (rois, labels_int32, bbox_targets,
    bbox_inside_weights, bbox_outside_weights); the matched-gt index var
    rides on ``rois.matched_gt`` for generate_mask_labels."""
    from paddle_tpu import framework as fw

    helper = LayerHelper("generate_proposal_labels")
    prog = helper.main_program
    outs = {
        s: [helper.create_variable_for_type_inference(
            "int32" if "Int" in s or s == "MatchedGtIndex" else rpn_rois.dtype)]
        for s in ["Rois", "LabelsInt32", "BboxTargets", "BboxInsideWeights",
                  "BboxOutsideWeights", "MatchedGtIndex"]
    }
    ins = {"RpnRois": [rpn_rois], "GtClasses": [gt_classes],
           "GtBoxes": [gt_boxes]}
    if is_crowd is not None:
        ins["IsCrowd"] = [is_crowd]
    if im_info is not None:
        ins["ImInfo"] = [im_info]
    helper.append_op(
        type="generate_proposal_labels", inputs=ins, outputs=outs,
        attrs={"batch_size_per_im": int(batch_size_per_im),
               "fg_fraction": float(fg_fraction), "fg_thresh": float(fg_thresh),
               "bg_thresh_hi": float(bg_thresh_hi),
               "bg_thresh_lo": float(bg_thresh_lo),
               "bbox_reg_weights": list(bbox_reg_weights),
               "class_nums": int(class_nums or 81),
               "use_random": bool(use_random),
               "is_cls_agnostic": bool(is_cls_agnostic),
               "seed": prog.next_seed()},
    )
    rois = outs["Rois"][0]
    for slot in outs:
        outs[slot][0].stop_gradient = True
    rois.matched_gt = outs["MatchedGtIndex"][0]
    return (rois, outs["LabelsInt32"][0], outs["BboxTargets"][0],
            outs["BboxInsideWeights"][0], outs["BboxOutsideWeights"][0])


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution):
    """reference: layers/detection.py generate_mask_labels.  DIVERGENCE:
    ``gt_segms`` is a [G, Hm, Wm] binary-mask tensor (rasterize COCO
    polygons host-side), not a polygon LoD; ``rois`` must come from
    generate_proposal_labels (carries .matched_gt)."""
    helper = LayerHelper("generate_mask_labels")
    mask_rois = helper.create_variable_for_type_inference(rois.dtype)
    has_mask = helper.create_variable_for_type_inference("int32")
    mask_int32 = helper.create_variable_for_type_inference("int32")
    matched = getattr(rois, "matched_gt", None)
    if matched is None:
        raise ValueError(
            "generate_mask_labels needs rois from generate_proposal_labels "
            "(the matched-gt index rides on the rois var)")
    ins = {"Rois": [rois], "LabelsInt32": [labels_int32],
           "MatchedGtIndex": [matched], "GtSegms": [gt_segms]}
    if im_info is not None:
        ins["ImInfo"] = [im_info]
    helper.append_op(
        type="generate_mask_labels", inputs=ins,
        outputs={"MaskRois": [mask_rois], "RoiHasMaskInt32": [has_mask],
                 "MaskInt32": [mask_int32]},
        attrs={"resolution": int(resolution), "num_classes": int(num_classes)},
    )
    for v in (mask_rois, has_mask, mask_int32):
        v.stop_gradient = True
    return mask_rois, has_mask, mask_int32


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd, im_info,
                            num_classes=1, positive_overlap=0.5,
                            negative_overlap=0.4):
    """reference: layers/detection.py retinanet_target_assign.  Padded
    analog: returns full-anchor masks (score labels, class targets,
    bbox targets, inside weights, fg count) instead of gathered compact
    tensors — see rpn_target_assign's docstring."""
    helper = LayerHelper("retinanet_target_assign")
    score_idx = helper.create_variable_for_type_inference("int32")
    tgt_lbl = helper.create_variable_for_type_inference("int32")
    tgt_bbox = helper.create_variable_for_type_inference(anchor_box.dtype)
    in_w = helper.create_variable_for_type_inference(anchor_box.dtype)
    s_w = helper.create_variable_for_type_inference(anchor_box.dtype)
    fg_num = helper.create_variable_for_type_inference("int32")
    ins = {"Anchor": [anchor_box], "GtBoxes": [gt_boxes]}
    if gt_labels is not None:
        ins["GtLabels"] = [gt_labels]
    helper.append_op(
        type="retinanet_target_assign", inputs=ins,
        outputs={"ScoreIndex": [score_idx], "TargetLabel": [tgt_lbl],
                 "TargetBBox": [tgt_bbox], "BBoxInsideWeight": [in_w],
                 "ScoreWeight": [s_w], "ForegroundNumber": [fg_num]},
        attrs={"positive_overlap": float(positive_overlap),
               "negative_overlap": float(negative_overlap)},
    )
    for v in (score_idx, tgt_lbl, tgt_bbox, in_w, s_w, fg_num):
        v.stop_gradient = True
    return score_idx, tgt_lbl, tgt_bbox, in_w, s_w, fg_num


def retinanet_detection_output(bboxes, scores, anchors, im_info=None,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """reference: layers/detection.py retinanet_detection_output."""
    helper = LayerHelper("retinanet_detection_output")
    out = helper.create_variable_for_type_inference(bboxes[0].dtype)
    helper.append_op(
        type="retinanet_detection_output",
        inputs={"BBoxes": list(bboxes), "Scores": list(scores),
                "Anchors": list(anchors)},
        outputs={"Out": [out]},
        attrs={"score_threshold": float(score_threshold),
               "nms_top_k": int(nms_top_k), "keep_top_k": int(keep_top_k),
               "nms_threshold": float(nms_threshold)},
    )
    out.stop_gradient = True
    return out


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0):
    """reference: layers/detection.py roi_perspective_transform."""
    helper = LayerHelper("roi_perspective_transform")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="roi_perspective_transform",
        inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out]},
        attrs={"transformed_height": int(transformed_height),
               "transformed_width": int(transformed_width),
               "spatial_scale": float(spatial_scale)},
    )
    return out
