"""Detection layers (reference: python/paddle/fluid/layers/detection.py —
prior_box, box_coder, iou_similarity, yolo_box, multiclass_nms...)."""
from __future__ import annotations

from paddle_tpu.layer_helper import LayerHelper

__all__ = ["prior_box", "box_coder", "iou_similarity", "yolo_box", "multiclass_nms"]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={
            "min_sizes": list(min_sizes),
            "max_sizes": list(max_sizes or []),
            "aspect_ratios": list(aspect_ratios),
            "variances": list(variance),
            "flip": flip,
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
        },
    )
    boxes.stop_gradient = var.stop_gradient = True
    return boxes, var


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, name=None):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference(target_box.dtype)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        type="box_coder",
        inputs=inputs,
        outputs={"OutputBox": [out]},
        attrs={"code_type": code_type, "box_normalized": box_normalized},
    )
    return out


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="iou_similarity",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"box_normalized": box_normalized},
    )
    return out


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, name=None):
    helper = LayerHelper("yolo_box", name=name)
    boxes = helper.create_variable_for_type_inference(x.dtype)
    scores = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="yolo_box",
        inputs={"X": [x], "ImgSize": [img_size]},
        outputs={"Boxes": [boxes], "Scores": [scores]},
        attrs={
            "anchors": list(anchors),
            "class_num": class_num,
            "conf_thresh": conf_thresh,
            "downsample_ratio": downsample_ratio,
        },
    )
    return boxes, scores


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=64,
                   keep_top_k=16, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    """Static-shape NMS: [N, keep_top_k, 6], label -1 = padding (see
    ops/detection_ops.py)."""
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    helper.append_op(
        type="multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out]},
        attrs={
            "score_threshold": score_threshold,
            "nms_top_k": nms_top_k,
            "keep_top_k": keep_top_k,
            "nms_threshold": nms_threshold,
            "normalized": normalized,
        },
    )
    out.stop_gradient = True
    return out
