"""Extended layer surface (reference: python/paddle/fluid/layers/nn.py
tail — the long fluid 1.5 API; per-function reference pointers below).

Wrappers over ops/extended_ops.py kernels plus compositions and
subsumed-identity shims where the TPU-native design already delivers
the semantics (SelectedRows helpers).
"""
from __future__ import annotations

import numpy as np

from paddle_tpu import framework
from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "selu", "lrn", "affine_channel", "conv3d", "conv3d_transpose", "pool3d",
    "adaptive_pool2d", "adaptive_pool3d", "resize_trilinear", "multiplex",
    "space_to_depth", "temporal_shift", "unfold", "cos_sim", "kldiv_loss",
    "rank_loss", "margin_rank_loss", "bpr_loss", "center_loss",
    "teacher_student_sigmoid_loss", "mean_iou", "dice_loss", "npair_loss",
    "affine_grid", "grid_sampler", "add_position_encoding", "shard_index",
    "hash", "sampling_id", "random_crop", "sequence_reshape",
    "sequence_scatter", "sequence_concat", "sequence_pad", "sequence_unpad",
    "sequence_slice", "unique_with_counts", "unique", "psroi_pool",
    "gaussian_random", "gaussian_random_batch_size_like",
    "uniform_random_batch_size_like", "sum", "rank", "size", "reduce_all",
    "reduce_any", "elementwise_mod", "elementwise_floordiv", "logical_xor",
    "image_resize_short", "autoincreased_step_counter",
    "get_tensor_from_selected_rows", "merge_selected_rows", "lod_reset",
    "lod_append", "beam_search", "beam_search_decode", "chunk_eval",
    "sampled_softmax_with_cross_entropy", "continuous_value_model",
    "filter_by_instag", "fsp_matrix", "deformable_conv", "dynamic_lstmp",
    "lstm", "similarity_focus", "var_conv_2d", "tree_conv",
    "deformable_roi_pooling", "diag", "eye", "linspace", "reverse",
    "has_inf", "has_nan", "tensor_array_to_tensor", "is_empty", "Print",
]


def _simple(op_type, ins, attrs=None, outs=("Out",), dtype=None, extra_vars=None):
    helper = LayerHelper(op_type)
    first = next(iter(ins.values()))[0]
    out_vars = {}
    for slot in outs:
        d = dtype or getattr(first, "dtype", "float32")
        if extra_vars and slot in extra_vars:
            d = extra_vars[slot]
        out_vars[slot] = helper.create_variable_for_type_inference(d)
    helper.append_op(
        type=op_type,
        inputs={k: [v for v in vs] for k, vs in ins.items()},
        outputs={k: [v] for k, v in out_vars.items()},
        attrs=attrs or {},
    )
    return [out_vars[s] for s in outs]


# -- activations / norms ---------------------------------------------------
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    """reference: layers/nn.py selu."""
    return _simple("selu", {"X": [x]}, {"scale": scale, "alpha": alpha})[0]


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    """reference: layers/nn.py lrn (note fluid layer default k=1.0)."""
    return _simple("lrn", {"X": [input]},
                   {"n": n, "k": k, "alpha": alpha, "beta": beta},
                   outs=("Out", "MidOut"))[0]


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None):
    """reference: layers/nn.py affine_channel."""
    return _simple("affine_channel", {"X": [x], "Scale": [scale], "Bias": [bias]},
                   {"data_layout": data_layout})[0]


# -- 3D conv/pool ----------------------------------------------------------
def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    """reference: layers/nn.py conv3d — NCDHW."""
    helper = LayerHelper("conv3d", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    c = int(input.shape[1])
    fs = filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 3
    w = helper.create_parameter(
        param_attr, shape=[num_filters, c // groups] + list(fs), dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="conv3d", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation,
               "groups": groups},
    )
    pre = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    """reference: layers/nn.py conv3d_transpose."""
    helper = LayerHelper("conv3d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c = int(input.shape[1])
    fs = filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 3
    w = helper.create_parameter(
        param_attr, shape=[c, num_filters // groups] + list(fs), dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="conv3d_transpose", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation,
               "groups": groups},
    )
    pre = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None):
    """reference: layers/nn.py pool3d."""
    return _simple(
        "pool3d", {"X": [input]},
        {"ksize": pool_size, "strides": pool_stride, "paddings": pool_padding,
         "pooling_type": pool_type, "global_pooling": global_pooling,
         "exclusive": exclusive},
    )[0]


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    """reference: layers/nn.py adaptive_pool2d."""
    if require_index:
        raise NotImplementedError("adaptive_pool2d require_index")
    return _simple("adaptive_pool2d", {"X": [input]},
                   {"pool_size": pool_size, "pooling_type": pool_type})[0]


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    """reference: layers/nn.py adaptive_pool3d — exact torch-style bins
    (floor/ceil window edges), non-divisible shapes included."""
    if require_index:
        raise NotImplementedError("adaptive_pool3d require_index")
    return _simple("adaptive_pool3d", {"X": [input]},
                   {"pool_size": pool_size, "pooling_type": pool_type})[0]


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True, align_mode=1):
    """reference: layers/nn.py resize_trilinear."""
    if out_shape is None:
        out_shape = [int(s * scale) for s in input.shape[2:]]
    return _simple(
        "trilinear_interp", {"X": [input]},
        {"out_d": int(out_shape[0]), "out_h": int(out_shape[1]),
         "out_w": int(out_shape[2])},
    )[0]


# -- rearrangement ---------------------------------------------------------
def multiplex(inputs, index):
    """reference: layers/nn.py multiplex."""
    helper = LayerHelper("multiplex")
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op(type="multiplex",
                     inputs={"X": list(inputs), "Ids": [index]},
                     outputs={"Out": [out]}, attrs={})
    return out


def space_to_depth(x, blocksize, name=None):
    """reference: layers/nn.py space_to_depth."""
    return _simple("space_to_depth", {"X": [x]}, {"blocksize": blocksize})[0]


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    """reference: layers/nn.py temporal_shift."""
    return _simple("temporal_shift", {"X": [x]},
                   {"seg_num": seg_num, "shift_ratio": shift_ratio})[0]


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """reference: layers/nn.py unfold (im2col)."""
    mk = lambda v: list(v) if isinstance(v, (list, tuple)) else [int(v)] * 2
    helper = LayerHelper("unfold")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="unfold", inputs={"X": [x]}, outputs={"Y": [out]},
        attrs={"kernel_sizes": mk(kernel_sizes), "strides": mk(strides),
               "paddings": mk(paddings), "dilations": mk(dilations)},
    )
    return out


# -- losses / metrics ------------------------------------------------------
def cos_sim(X, Y):
    """reference: layers/nn.py cos_sim."""
    return _simple("cos_sim", {"X": [X], "Y": [Y]},
                   outs=("Out", "XNorm", "YNorm"))[0]


def kldiv_loss(x, target, reduction="mean", name=None):
    """reference: layers/nn.py kldiv_loss."""
    helper = LayerHelper("kldiv_loss")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="kldiv_loss",
                     inputs={"X": [x], "Target": [target]},
                     outputs={"Loss": [out]}, attrs={"reduction": reduction})
    return out


def rank_loss(label, left, right, name=None):
    """reference: layers/nn.py rank_loss."""
    return _simple("rank_loss",
                   {"Label": [label], "Left": [left], "Right": [right]})[0]


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    """reference: layers/nn.py margin_rank_loss."""
    return _simple("margin_rank_loss",
                   {"Label": [label], "X1": [left], "X2": [right]},
                   {"margin": margin}, outs=("Out", "Activated"))[0]


def bpr_loss(input, label, name=None):
    """reference: layers/nn.py bpr_loss."""
    return _simple("bpr_loss", {"X": [input], "Label": [label]})[0]


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    """reference: layers/nn.py center_loss — creates the centers param;
    the kernel's CentersOut writes back through the stateful output."""
    from paddle_tpu import initializer
    from paddle_tpu.layers import tensor as ltensor

    helper = LayerHelper("center_loss", param_attr=param_attr)
    dim = int(input.shape[-1])
    centers = helper.create_parameter(
        param_attr, shape=[num_classes, dim], dtype=input.dtype,
        default_initializer=initializer.Constant(0.0))
    centers.stop_gradient = True
    rate = ltensor.fill_constant([1], input.dtype, float(alpha))
    loss = helper.create_variable_for_type_inference(input.dtype)
    diff = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="center_loss",
        inputs={"X": [input], "Label": [label], "Centers": [centers],
                "CenterUpdateRate": [rate]},
        outputs={"Loss": [loss], "SampleCenterDiff": [diff],
                 "CentersOut": [centers]},
        attrs={"need_update": bool(update_center)},
    )
    return loss


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """reference: layers/nn.py teacher_student_sigmoid_loss."""
    helper = LayerHelper("teacher_student_sigmoid_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="teacher_student_sigmoid_loss",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]}, attrs={})
    return out


def mean_iou(input, label, num_classes):
    """reference: layers/nn.py mean_iou — returns (miou, wrong, correct)."""
    helper = LayerHelper("mean_iou")
    miou = helper.create_variable_for_type_inference("float32")
    wrong = helper.create_variable_for_type_inference("int32")
    correct = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="mean_iou", inputs={"Predictions": [input], "Labels": [label]},
        outputs={"OutMeanIou": [miou], "OutWrong": [wrong],
                 "OutCorrect": [correct]},
        attrs={"num_classes": int(num_classes)},
    )
    return miou, wrong, correct


def dice_loss(input, label, epsilon=1e-5):
    """reference: layers/nn.py dice_loss — composition over existing ops."""
    from paddle_tpu.layers import tensor as ltensor

    label = ltensor.cast(label, input.dtype)
    inter = ltensor.reduce_sum(input * label)
    union = ltensor.reduce_sum(input) + ltensor.reduce_sum(label)
    return 1.0 - (2.0 * inter + epsilon) / (union + epsilon)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """reference: layers/nn.py npair_loss — cross-entropy over the
    anchor@positive^T similarity matrix with equal-label soft targets,
    plus L2 on the embeddings."""
    from paddle_tpu.layers import nn, tensor as ltensor

    sim = nn.matmul(anchor, positive, transpose_y=True)  # [B, B]
    lab_col = ltensor.cast(ltensor.reshape(labels, shape=[-1, 1]), "float32")
    helper = LayerHelper("npair_equal")
    eqv = helper.create_variable_for_type_inference("bool")
    helper.append_op(type="equal",
                     inputs={"X": [lab_col],
                             "Y": [ltensor.transpose(lab_col, [1, 0])]},
                     outputs={"Out": [eqv]}, attrs={})
    tgt = ltensor.cast(eqv, "float32")
    tgt = tgt / ltensor.reduce_sum(tgt, dim=1, keep_dim=True)
    xent = nn.softmax_with_cross_entropy(sim, tgt, soft_label=True)
    l2 = ltensor.reduce_mean(
        ltensor.reduce_sum(anchor * anchor, dim=1)
        + ltensor.reduce_sum(positive * positive, dim=1)
    )
    return ltensor.reduce_mean(xent) + l2 * l2_reg


# -- grid / positional -----------------------------------------------------
def affine_grid(theta, out_shape, name=None):
    """reference: layers/nn.py affine_grid."""
    helper = LayerHelper("affine_grid")
    out = helper.create_variable_for_type_inference(theta.dtype)
    helper.append_op(
        type="affine_grid", inputs={"Theta": [theta]},
        outputs={"Output": [out]},
        attrs={"output_shape": [int(s) for s in out_shape]},
    )
    return out


def grid_sampler(x, grid, name=None):
    """reference: layers/nn.py grid_sampler."""
    helper = LayerHelper("grid_sampler")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="grid_sampler",
                     inputs={"X": [x], "Grid": [grid]},
                     outputs={"Output": [out]}, attrs={})
    return out


def add_position_encoding(input, alpha, beta, name=None):
    """reference: layers/nn.py add_position_encoding."""
    return _simple("add_position_encoding", {"X": [input]},
                   {"alpha": float(alpha), "beta": float(beta)})[0]


# -- id transforms ---------------------------------------------------------
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """reference: layers/nn.py shard_index."""
    return _simple("shard_index", {"X": [input]},
                   {"index_num": index_num, "nshards": nshards,
                    "shard_id": shard_id, "ignore_value": ignore_value},
                   dtype=input.dtype)[0]


def hash(input, hash_size, num_hash=1, name=None):
    """reference: layers/nn.py hash (bucketed id hashing; see the op's
    docstring for the xxhash divergence note)."""
    return _simple("hash", {"X": [input]},
                   {"mod_by": int(hash_size), "num_hash": int(num_hash)},
                   dtype="int64")[0]


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    """reference: layers/nn.py sampling_id."""
    return _simple("sampling_id", {"X": [x]}, {"seed": int(seed)},
                   dtype="int64")[0]


def random_crop(x, shape, seed=None):
    """reference: layers/nn.py random_crop."""
    prog = framework.default_main_program()
    return _simple("random_crop", {"X": [x]},
                   {"shape": [int(s) for s in shape],
                    "seed": int(seed) if seed is not None else prog.next_seed()},
                   outs=("Out", "SeedOut"), dtype=x.dtype)[0]


# -- sequence extensions ---------------------------------------------------
def sequence_reshape(input, new_dim, seq_len=None):
    """reference: layers/nn.py sequence_reshape."""
    helper = LayerHelper("sequence_reshape")
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input]}
    outs = {"Out": [out]}
    new_len = None
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
        new_len = helper.create_variable_for_type_inference("int32")
        outs["OutSeqLen"] = [new_len]
    helper.append_op(type="sequence_reshape", inputs=ins, outputs=outs,
                     attrs={"new_dim": int(new_dim)})
    return (out, new_len) if seq_len is not None else out


def sequence_scatter(input, index, updates, seq_len=None, name=None):
    """reference: layers/nn.py sequence_scatter."""
    helper = LayerHelper("sequence_scatter")
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input], "Ids": [index], "Updates": [updates]}
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op(type="sequence_scatter", inputs=ins,
                     outputs={"Out": [out]}, attrs={})
    return out


def sequence_concat(input, name=None):
    """reference: layers/sequence_concat — concat along time."""
    helper = LayerHelper("sequence_concat")
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(type="sequence_concat", inputs={"X": list(input)},
                     outputs={"Out": [out]}, attrs={})
    return out


def sequence_pad(x, pad_value, maxlen=None, seq_len=None, name=None):
    """reference: layers/nn.py sequence_pad — identity on the padded
    encoding; returns (x, lengths)."""
    helper = LayerHelper("sequence_pad")
    out = helper.create_variable_for_type_inference(x.dtype)
    length = helper.create_variable_for_type_inference("int64")
    ins = {"X": [x], "PadValue": [pad_value]}
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op(type="sequence_pad", inputs=ins,
                     outputs={"Out": [out], "Length": [length]}, attrs={})
    return out, length


def sequence_unpad(x, length, name=None):
    """reference: layers/nn.py sequence_unpad — identity view on the
    padded encoding (lengths travel alongside)."""
    helper = LayerHelper("sequence_unpad")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="sequence_unpad",
                     inputs={"X": [x], "Length": [length]},
                     outputs={"Out": [out]}, attrs={})
    return out


def sequence_slice(input, offset, length, name=None):
    """reference: layers/nn.py sequence_slice."""
    helper = LayerHelper("sequence_slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="sequence_slice",
                     inputs={"X": [input], "Offset": [offset],
                             "Length": [length]},
                     outputs={"Out": [out]}, attrs={})
    return out


def unique_with_counts(x, dtype="int32"):
    """reference: layers/nn.py unique_with_counts — padded-static
    variant: Out is len(x) long with UniqueCount real entries."""
    helper = LayerHelper("unique_with_counts")
    out = helper.create_variable_for_type_inference(x.dtype)
    index = helper.create_variable_for_type_inference(dtype)
    count = helper.create_variable_for_type_inference(dtype)
    ucount = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="unique_with_counts", inputs={"X": [x]},
        outputs={"Out": [out], "Index": [index], "Count": [count],
                 "UniqueCount": [ucount]},
        attrs={},
    )
    return out, index, count


def unique(x, dtype="int32"):
    """reference: layers/nn.py unique."""
    out, index, _ = unique_with_counts(x, dtype)
    return out, index


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, name=None):
    """reference: layers/nn.py psroi_pool."""
    helper = LayerHelper("psroi_pool")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="psroi_pool", inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out]},
        attrs={"output_channels": int(output_channels),
               "spatial_scale": float(spatial_scale),
               "pooled_height": int(pooled_height),
               "pooled_width": int(pooled_width)},
    )
    return out


# -- random / misc wrappers over existing kernels --------------------------
def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    """reference: layers/ops.py gaussian_random."""
    prog = framework.default_main_program()
    return _simple(
        "gaussian_random", {"ShapeLike": []},
        {"shape": [int(s) for s in shape], "mean": float(mean),
         "std": float(std), "seed": int(seed) or prog.next_seed(),
         "dtype": dtype},
        dtype=dtype)[0]


def gaussian_random_batch_size_like(input, shape, mean=0.0, std=1.0,
                                    input_dim_idx=0, output_dim_idx=0,
                                    seed=0, dtype="float32"):
    """reference: layers/nn.py gaussian_random_batch_size_like — batch
    dim copied from input at run time via ShapeLike."""
    prog = framework.default_main_program()
    return _simple(
        "gaussian_random", {"ShapeLike": [input]},
        {"shape": [int(s) for s in shape], "mean": float(mean),
         "std": float(std), "seed": int(seed) or prog.next_seed(),
         "dtype": dtype, "input_dim_idx": int(input_dim_idx),
         "output_dim_idx": int(output_dim_idx)},
        dtype=dtype)[0]


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    """reference: layers/nn.py uniform_random_batch_size_like."""
    prog = framework.default_main_program()
    return _simple(
        "uniform_random", {"ShapeLike": [input]},
        {"shape": [int(s) for s in shape], "min": float(min),
         "max": float(max), "seed": int(seed) or prog.next_seed(),
         "dtype": dtype, "input_dim_idx": int(input_dim_idx),
         "output_dim_idx": int(output_dim_idx)},
        dtype=dtype)[0]


def sum(x):
    """reference: layers/tensor.py sum (elementwise accumulate)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    return _simple("sum", {"X": list(xs)})[0]


def rank(input):
    """reference: layers/nn.py rank — static ndim as a constant."""
    from paddle_tpu.layers import tensor as ltensor

    return ltensor.fill_constant([1], "int32", len(input.shape))


def size(input):
    """reference: layers/nn.py size — element count (static dims only)."""
    from paddle_tpu.layers import tensor as ltensor

    n = 1
    for s in input.shape:
        n *= int(s)
    if n < 0:
        raise ValueError("size() needs a fully static shape, got %s"
                         % (input.shape,))
    return ltensor.fill_constant([1], "int64", n)


def reduce_all(input, dim=None, keep_dim=False, name=None):
    """reference: layers/nn.py reduce_all."""
    return _simple("reduce_all", {"X": [input]},
                   {"dim": dim if dim is None or isinstance(dim, list) else [dim],
                    "keep_dim": keep_dim, "reduce_all": dim is None},
                   dtype="bool")[0]


def reduce_any(input, dim=None, keep_dim=False, name=None):
    """reference: layers/nn.py reduce_any."""
    return _simple("reduce_any", {"X": [input]},
                   {"dim": dim if dim is None or isinstance(dim, list) else [dim],
                    "keep_dim": keep_dim, "reduce_all": dim is None},
                   dtype="bool")[0]


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    """reference: layers/nn.py elementwise_mod."""
    return _simple("elementwise_mod", {"X": [x], "Y": [y]}, {"axis": axis})[0]


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    """reference: layers/nn.py elementwise_floordiv."""
    return _simple("elementwise_floordiv", {"X": [x], "Y": [y]}, {"axis": axis})[0]


def logical_xor(x, y, out=None, name=None):
    """reference: layers/nn.py logical_xor."""
    return _simple("logical_xor", {"X": [x], "Y": [y]}, dtype="bool")[0]


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """reference: layers/nn.py image_resize_short — resize so the short
    side hits out_short_len."""
    from paddle_tpu.layers import nn

    h, w = int(input.shape[2]), int(input.shape[3])
    short = min(h, w)
    oh = int(round(h * out_short_len / short))
    ow = int(round(w * out_short_len / short))
    return nn.image_resize(input, out_shape=[oh, ow], resample=resample)


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """reference: layers/nn.py autoincreased_step_counter — persistable
    int64 counter bumped by ``step`` each execution."""
    from paddle_tpu import initializer
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("global_step_counter")
    block = helper.main_program.global_block()
    name = counter_name or "@STEP_COUNTER@"
    counter = block.vars.get(name)
    if counter is None:
        counter = block.create_var(name=name, shape=[1], dtype="int64",
                                   persistable=True, stop_gradient=True)
        helper.set_variable_initializer(
            counter, initializer.Constant(float(begin - step)))
    helper.append_op(
        type="scale", inputs={"X": [counter]}, outputs={"Out": [counter]},
        attrs={"scale": 1.0, "bias": float(step)},
    )
    return counter


# -- subsumed SelectedRows helpers ----------------------------------------
def get_tensor_from_selected_rows(x, name=None):
    """reference: layers/nn.py get_tensor_from_selected_rows.  On this
    build sparse row-grads are subsumed by the PS push path (PARITY #14)
    — dense vars pass through unchanged."""
    return x


def merge_selected_rows(x, name=None):
    """reference: layers/nn.py merge_selected_rows — duplicate-row
    merging happens inside PSClient.push_sparse on this build; identity
    for dense vars."""
    return x


def lod_reset(x, y=None, target_lod=None):
    """reference: layers/nn.py lod_reset.  Padded-shim: lengths travel
    as a companion var, so this RETURNS the new lengths var to pass to
    downstream sequence ops (x itself is unchanged)."""
    from paddle_tpu.layers import tensor as ltensor

    if y is not None:
        return x, y
    if target_lod is None:
        raise ValueError("lod_reset needs y or target_lod")
    lengths = [int(b) - int(a) for a, b in zip(target_lod, target_lod[1:])] \
        if len(target_lod) and target_lod[0] == 0 else [int(t) for t in target_lod]
    return x, ltensor.assign(np.asarray(lengths, "int32"))


def lod_append(x, level):
    """reference: layers/nn.py lod_append — nested-LoD shim: returns the
    inner-length var for a new nested level."""
    from paddle_tpu.layers import tensor as ltensor

    return x, ltensor.assign(np.asarray(level, "int32"))


# -- decode / eval wrappers ------------------------------------------------
def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None, return_parent_idx=False):
    """Per-step beam selection inside a While decode loop (reference:
    layers/nn.py beam_search:4406, beam_search_op.cc).  Static-shape
    mapping: every source keeps a fixed beam_size lane width and finished
    beams persist via end_id masking (see the op docstring); seed the
    first step by feeding lane 0 score 0 and the other lanes -1e9.  The
    whole-search alternative is paddle_tpu.decoding.beam_search (one
    lax.scan module)."""
    helper = LayerHelper("beam_search")
    sel_ids = helper.create_variable_for_type_inference("int64")
    sel_sc = helper.create_variable_for_type_inference(scores.dtype)
    parent = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="beam_search",
        inputs={"pre_ids": [pre_ids], "pre_scores": [pre_scores],
                "ids": [ids], "scores": [scores]},
        outputs={"selected_ids": [sel_ids], "selected_scores": [sel_sc],
                 "parent_idx": [parent]},
        attrs={"beam_size": int(beam_size), "end_id": int(end_id),
               "level": int(level), "is_accumulated": bool(is_accumulated)},
    )
    if return_parent_idx:
        return sel_ids, sel_sc, parent
    return sel_ids, sel_sc


def beam_search_decode(ids, scores, beam_size, end_id, name=None,
                       parents=None):
    """Backtrack the per-step arrays into full sequences (reference:
    layers/nn.py beam_search_decode, beam_search_decode_op.cc).

    ``ids``/``scores`` are the stacked tensor-arrays [T, B*K, 1] the
    decode loop array_write'd; ``parents`` [T, B*K] is the matching array
    of beam_search parent_idx writes — the static encoding's replacement
    for the reference's LoD-encoded parentage (pass it; only a loop that
    never reorders beams could omit it).  Returns SentenceIds [B, K, T]
    and SentenceScores [B, K], best-first."""
    if parents is None:
        raise ValueError(
            "beam_search_decode on the static encoding needs the parents "
            "array (array_write each step's beam_search parent_idx)"
        )
    helper = LayerHelper("beam_search_decode")
    sent = helper.create_variable_for_type_inference("int64")
    sc = helper.create_variable_for_type_inference(scores.dtype)
    helper.append_op(
        type="beam_search_decode",
        inputs={"Ids": [ids], "Scores": [scores], "Parents": [parents]},
        outputs={"SentenceIds": [sent], "SentenceScores": [sc]},
        attrs={"beam_size": int(beam_size), "end_id": int(end_id)},
    )
    return sent, sc


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """reference: layers/nn.py chunk_eval (chunk_eval_op.h) — in-graph
    chunk-level precision/recall/F1 on padded [B, T] predictions+labels
    (+ optional per-row seq_length).  Returns the reference's 6-tuple
    (precision, recall, f1, num_infer, num_label, num_correct); feed the
    counts to metrics.ChunkEvaluator for streaming aggregation."""
    helper = LayerHelper("chunk_eval")
    outs = {
        n: helper.create_variable_for_type_inference(
            "float32" if i < 3 else "int64"
        )
        for i, n in enumerate(
            ["Precision", "Recall", "F1-Score", "NumInferChunks",
             "NumLabelChunks", "NumCorrectChunks"]
        )
    }
    ins = {"Inference": [input], "Label": [label]}
    if seq_length is not None:
        ins["SeqLength"] = [seq_length]
    helper.append_op(
        type="chunk_eval", inputs=ins,
        outputs={k: [v] for k, v in outs.items()},
        attrs={"chunk_scheme": chunk_scheme,
               "num_chunk_types": int(num_chunk_types),
               "excluded_chunk_types": list(excluded_chunk_types or [])},
    )
    return (outs["Precision"], outs["Recall"], outs["F1-Score"],
            outs["NumInferChunks"], outs["NumLabelChunks"],
            outs["NumCorrectChunks"])


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """reference: layers/nn.py sampled_softmax_with_cross_entropy
    (sample_logits_op.cc + softmax CE) — fused kernel, see the op's
    docstring.  Returns the [N, 1] loss."""
    if use_customized_samples and (
        customized_samples is None or customized_probabilities is None
    ):
        raise ValueError(
            "sampled_softmax: use_customized_samples=True needs both "
            "customized_samples and customized_probabilities"
        )
    helper = LayerHelper("sampled_softmax_with_cross_entropy")
    loss = helper.create_variable_for_type_inference(logits.dtype)
    ins = {"Logits": [logits], "Labels": [label]}
    if use_customized_samples:
        ins["CustomizedSamples"] = [customized_samples]
        ins["CustomizedProbabilities"] = [customized_probabilities]
    helper.append_op(
        type="sampled_softmax_with_cross_entropy", inputs=ins,
        outputs={"Loss": [loss]},
        attrs={"num_samples": int(num_samples), "num_true": int(num_true),
               "remove_accidental_hits": bool(remove_accidental_hits),
               "seed": int(seed)},
    )
    return loss


# -- CTR / distillation / deformable / LSTM family -------------------------
def continuous_value_model(input, cvm, use_cvm=True):
    """reference: layers/nn.py continuous_value_model (cvm_op.h)."""
    helper = LayerHelper("cvm")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="cvm", inputs={"X": [input], "CVM": [cvm]},
                     outputs={"Y": [out]}, attrs={"use_cvm": bool(use_cvm)})
    return out


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True):
    """reference: layers/nn.py filter_by_instag — static-shape packed
    variant (see the op docstring): returns (out, loss_weight)."""
    helper = LayerHelper("filter_by_instag")
    out = helper.create_variable_for_type_inference(ins.dtype)
    loss_weight = helper.create_variable_for_type_inference(ins.dtype)
    index_map = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="filter_by_instag",
        inputs={"Ins": [ins], "Ins_tag": [ins_tag], "Filter_tag": [filter_tag]},
        outputs={"Out": [out], "LossWeight": [loss_weight],
                 "IndexMap": [index_map]},
        attrs={"is_lod": bool(is_lod)},
    )
    return out, loss_weight


def fsp_matrix(x, y):
    """reference: layers/nn.py fsp_matrix (fsp_op.cc)."""
    helper = LayerHelper("fsp")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="fsp", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={})
    return out


def deformable_conv(input, offset, mask, num_filters, filter_size, stride=1,
                    padding=0, dilation=1, groups=None, deformable_groups=None,
                    im2col_step=None, param_attr=None, bias_attr=None,
                    modulated=True, name=None):
    """reference: layers/nn.py deformable_conv (v2 modulated / v1 when
    mask is None)."""
    helper = LayerHelper("deformable_conv", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    c = int(input.shape[1])
    fs = filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 2
    w = helper.create_parameter(
        param_attr, shape=[num_filters, c // (groups or 1)] + list(fs),
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    mk = lambda v: list(v) if isinstance(v, (list, tuple)) else [int(v)] * 2
    ins = {"Input": [input], "Offset": [offset], "Filter": [w]}
    if modulated and mask is not None:
        ins["Mask"] = [mask]
    helper.append_op(
        type="deformable_conv", inputs=ins, outputs={"Output": [out]},
        attrs={"strides": mk(stride), "paddings": mk(padding),
               "dilations": mk(dilation), "groups": groups or 1,
               "deformable_groups": deformable_groups or 1},
    )
    return helper.append_bias_op(out, dim_start=1, dim_end=2)


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None, seq_len=None):
    """reference: layers/nn.py dynamic_lstmp — LSTM with recurrent
    projection; input must be pre-projected to [B, T, 4*hidden]
    (size = 4*hidden)."""
    helper = LayerHelper("dynamic_lstmp", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    hidden = size // 4
    w = helper.create_parameter(param_attr, shape=[proj_size, size], dtype=dtype)
    w_proj = helper.create_parameter(param_attr, shape=[hidden, proj_size],
                                     dtype=dtype)
    bias_w = 7 * hidden if use_peepholes else 4 * hidden
    b = helper.create_parameter(bias_attr, shape=[1, bias_w], dtype=dtype,
                                is_bias=True)
    proj = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    ins = {"Input": [input], "Weight": [w], "ProjWeight": [w_proj], "Bias": [b]}
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op(
        type="dynamic_lstmp", inputs=ins,
        outputs={"Projection": [proj], "Cell": [cell]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation,
               "proj_activation": proj_activation},
    )
    return proj, cell


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """reference: layers/nn.py lstm (the cudnn multi-layer LSTM) — built
    as stacked fc->dynamic_lstm layers (+ reversed pass concat when
    bidirectional); XLA fuses the stack into one module, which is the
    cudnn-speed path on TPU."""
    from paddle_tpu.layers import nn, rnn as lrnn, tensor as ltensor

    h = input
    last_h_list, last_c_list = [], []
    for _ in range(num_layers):
        proj = nn.fc(h, hidden_size * 4, num_flatten_dims=2, bias_attr=False)
        fwd, fwd_c = lrnn.dynamic_lstm(proj, hidden_size * 4, use_peepholes=False)
        if is_bidirec:
            projb = nn.fc(h, hidden_size * 4, num_flatten_dims=2, bias_attr=False)
            bwd, bwd_c = lrnn.dynamic_lstm(projb, hidden_size * 4,
                                           use_peepholes=False, is_reverse=True)
            h = ltensor.concat([fwd, bwd], axis=2)
            last_c_list += [nn.sequence_last_step(fwd_c),
                            nn.sequence_last_step(bwd_c)]
        else:
            h = fwd
            last_c_list.append(nn.sequence_last_step(fwd_c))
        if dropout_prob and not is_test:
            h = nn.dropout(h, dropout_prob)
        last_h_list.append(nn.sequence_last_step(h))
    last_hidden = ltensor.stack(last_h_list, axis=0)
    last_cell = ltensor.stack(last_c_list, axis=0)
    return h, last_hidden, last_cell


def similarity_focus(input, axis, indexes, name=None):
    """reference: layers/nn.py similarity_focus."""
    return _simple("similarity_focus", {"X": [input]},
                   {"axis": int(axis), "indexes": [int(i) for i in indexes]})[0]


def var_conv_2d(input, row, col, input_channel, output_channel, filter_size,
                stride=1, param_attr=None, act=None, dtype="float32",
                name=None):
    """reference: layers/nn.py var_conv_2d — variable-size 2D conv over
    per-sample (row, col) extents (padded-batch masked conv here)."""
    helper = LayerHelper("var_conv_2d", param_attr=param_attr, act=act,
                         name=name)
    fs = filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 2
    st = stride if isinstance(stride, (list, tuple)) else [stride] * 2
    w = helper.create_parameter(
        param_attr, shape=[output_channel, input_channel * fs[0] * fs[1]],
        dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="var_conv_2d",
        inputs={"X": [input], "ROW": [row], "COLUMN": [col], "W": [w]},
        outputs={"Out": [out]},
        attrs={"InputChannel": int(input_channel),
               "OutputChannel": int(output_channel),
               "KernelH": int(fs[0]), "KernelW": int(fs[1]),
               "StrideH": int(st[0]), "StrideW": int(st[1])},
    )
    return helper.append_activation(out)


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1, max_depth=2,
              act="tanh", param_attr=None, bias_attr=None, name=None):
    """reference: layers/nn.py tree_conv (TBCNN)."""
    helper = LayerHelper("tree_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    feature_size = int(nodes_vector.shape[-1])
    w = helper.create_parameter(
        param_attr, shape=[feature_size, 3, output_size, num_filters],
        dtype=nodes_vector.dtype)
    out = helper.create_variable_for_type_inference(nodes_vector.dtype)
    helper.append_op(
        type="tree_conv",
        inputs={"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
                "Filter": [w]},
        outputs={"Out": [out]},
        attrs={"max_depth": int(max_depth)},
    )
    pre = helper.append_bias_op(out, dim_start=2)
    return helper.append_activation(pre)


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=[1, 1],
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1, position_sensitive=False,
                           name=None):
    """reference: layers/nn.py deformable_roi_pooling
    (deformable_psroi_pooling_op.cc)."""
    helper = LayerHelper("deformable_roi_pooling")
    out = helper.create_variable_for_type_inference(input.dtype)
    top = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Input": [input], "ROIs": [rois]}
    if not no_trans and trans is not None:
        ins["Trans"] = [trans]
    helper.append_op(
        type="deformable_psroi_pooling", inputs=ins,
        outputs={"Output": [out], "TopCount": [top]},
        attrs={"no_trans": bool(no_trans),
               "spatial_scale": float(spatial_scale),
               "pooled_height": int(pooled_height),
               "pooled_width": int(pooled_width),
               "sample_per_part": int(sample_per_part),
               "trans_std": float(trans_std)},
    )
    return out


# -- tensor-namespace tail (reference: layers/tensor.py) -------------------
def diag(diagonal):
    """reference: layers/tensor.py diag."""
    helper = LayerHelper("diag")
    out = helper.create_variable_for_type_inference(diagonal.dtype)
    helper.append_op(type="diag", inputs={"Diagonal": [diagonal]},
                     outputs={"Out": [out]}, attrs={})
    return out


def eye(num_rows, num_columns=None, batch_shape=None, dtype="float32"):
    """reference: layers/tensor.py eye."""
    from paddle_tpu.layers import tensor as ltensor

    num_columns = num_columns or num_rows
    e = np.eye(int(num_rows), int(num_columns)).astype(dtype)
    if batch_shape:
        e = np.broadcast_to(e, list(batch_shape) + list(e.shape)).copy()
    return ltensor.assign(e)


def linspace(start, stop, num, dtype="float32"):
    """reference: layers/tensor.py linspace."""
    from paddle_tpu.layers import tensor as ltensor

    return ltensor.assign(np.linspace(float(start), float(stop), int(num),
                                      dtype=dtype))


def reverse(x, axis):
    """reference: layers/tensor.py reverse."""
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="reverse", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"axis": axis if isinstance(axis, list) else [axis]})
    return out


def has_inf(x):
    """reference: layers/tensor.py has_inf."""
    return _simple("has_inf", {"X": [x]}, dtype="bool")[0]


def has_nan(x):
    """reference: layers/tensor.py has_nan."""
    return _simple("has_nan", {"X": [x]}, dtype="bool")[0]


def tensor_array_to_tensor(input, axis=1, name=None):
    """reference: layers/tensor.py tensor_array_to_tensor — concat the
    (static pre-sized) array shim along axis; returns (out, sizes)."""
    from paddle_tpu.layers import tensor as ltensor

    vals = input if isinstance(input, (list, tuple)) else list(input)
    out = ltensor.concat(list(vals), axis=axis)
    sizes = ltensor.assign(
        np.asarray([int(v.shape[axis]) for v in vals], "int32"))
    return out, sizes


def is_empty(x, cond=None):
    """reference: layers/control_flow.py is_empty — static emptiness on
    this build (shapes are compile-time)."""
    from paddle_tpu.layers import tensor as ltensor

    n = 1
    for s in x.shape:
        n *= int(s)
    return ltensor.assign(np.asarray([n == 0]))


def Print(input, first_n=-1, message=None, summarize=-1, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_lod=True, print_phase="both"):
    """reference: layers/control_flow.py Print — host-side print at the
    op's position via the debug print callback."""
    return _simple("print", {"X": [input]},
                   {"message": message or "", "first_n": int(first_n),
                    "summarize": int(summarize)})[0]
