"""NN layers — the fluid layers API (reference: python/paddle/fluid/layers/nn.py).

Each function builds graph ops; no computation happens here.  Reference
line pointers are given per function.
"""
from __future__ import annotations

import numpy as np

from paddle_tpu import framework
from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "fc",
    "embedding",
    "conv2d",
    "conv2d_transpose",
    "pool2d",
    "batch_norm",
    "layer_norm",
    "group_norm",
    "dropout",
    "softmax",
    "log_softmax",
    "cross_entropy",
    "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits",
    "square_error_cost",
    "huber_loss",
    "log_loss",
    "matmul",
    "mul",
    "relu",
    "prelu",
    "l2_normalize",
    "one_hot",
    "topk",
    "accuracy",
    "auc",
    "sequence_pool",
    "sequence_softmax",
    "sequence_expand",
    "sequence_reverse",
    "sequence_mask",
    "im2sequence",
    "maxout",
    "pad",
    "pad2d",
    "label_smooth",
    "clip",
    "clip_by_norm",
    "mean",
    "smooth_l1",
    "warpctc",
    "sequence_conv",
    "sequence_erase",
    "sequence_enumerate",
    "sequence_expand_as",
    "sequence_first_step",
    "sequence_last_step",
    "nce",
    "hsigmoid",
    "lstm_unit",
    "gru_unit",
    "image_resize",
    "resize_bilinear",
    "resize_nearest",
    "pixel_shuffle",
    "shuffle_channel",
    "crop",
    "pad_constant_like",
    "py_func",
    "linear_chain_crf",
    "crf_decoding",
    "spectral_norm",
    "data_norm",
    "row_conv",
    "bilinear_tensor_product",
    "edit_distance",
    "ctc_greedy_decoder",
    "nested_sequence_pool",
]


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None, act=None, name=None):
    """Fully-connected (reference: layers/nn.py:223): mul + sum + bias + act."""
    helper = LayerHelper("fc", input=input, param_attr=param_attr, bias_attr=bias_attr, act=act, name=name)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    param_attrs = param_attr if isinstance(param_attr, (list, tuple)) else [param_attr] * len(inputs)
    mul_results = []
    for inp, pattr in zip(inputs, param_attrs):
        in_dims = inp.shape
        w_in = int(np.prod(in_dims[num_flatten_dims:]))
        w = helper.create_parameter(pattr, shape=[w_in, size], dtype=inp.dtype)
        tmp = helper.create_variable_for_type_inference(inp.dtype)
        helper.append_op(
            type="mul",
            inputs={"X": [inp], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(inputs[0].dtype)
        helper.append_op(type="sum", inputs={"X": mul_results}, outputs={"Out": [pre_bias]})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(
    input,
    size,
    is_sparse=False,
    is_distributed=False,
    padding_idx=None,
    param_attr=None,
    dtype="float32",
):
    """reference: layers/nn.py:449.

    ``is_distributed=True``: the table does NOT live in HBM — rows are
    served by the parameter server (distributed/ps.py) and prefetched
    per batch (reference: transpiler/distribute_lookup_table.py +
    parameter_prefetch.cc).  The layer records table metadata on the
    program; bind servers with
    ``paddle_tpu.distributed.bind_distributed_tables(program, endpoints)``
    and the executor handles pull-before/push-after each step.  The ids
    must be a feed of the step.  Otherwise the lookup is a dense HBM
    gather."""
    helper = LayerHelper("embedding", param_attr=param_attr)
    if is_distributed:
        from paddle_tpu import unique_name as _un
        from paddle_tpu.param_attr import ParamAttr

        block = helper.main_program.current_block()
        attr = param_attr if isinstance(param_attr, ParamAttr) else ParamAttr(name=param_attr)
        table_name = attr.name or _un.generate("dist_emb_table")
        rows = block.create_var(
            name=_un.generate(table_name + "@PREFETCH"),
            shape=[-1, size[1]], dtype=dtype, stop_gradient=False,
        )
        ids_shape = tuple(input.shape or ())
        local_shape = ids_shape[:-1] if ids_shape and ids_shape[-1] == 1 else ids_shape
        local = block.create_var(
            name=_un.generate(table_name + "@LOCALIDS"),
            shape=list(local_shape) or [-1], dtype="int32", stop_gradient=True,
        )
        tmp = helper.create_variable_for_type_inference(dtype)
        pad = -1 if padding_idx is None else (padding_idx if padding_idx >= 0 else size[0] + padding_idx)
        helper.append_op(
            type="distributed_lookup_table",
            inputs={"Rows": [rows], "Ids": [local], "OrigIds": [input]},
            outputs={"Out": [tmp]},
            attrs={"table": table_name, "padding_idx": pad},
        )
        prog = helper.main_program
        if not hasattr(prog, "_distributed_tables"):
            prog._distributed_tables = {}
        # keyed by the prefetch var (unique per lookup SITE) — several
        # sites may share one server table (tied embeddings)
        prog._distributed_tables[rows.name] = {
            "table": table_name,
            "dim": int(size[1]),
            "height": int(size[0]),
            "ids_name": input.name,
            "rows_name": rows.name,
            "local_name": local.name,
            "squeeze_last": bool(ids_shape and ids_shape[-1] == 1),
        }
        return tmp
    w = helper.create_parameter(param_attr, shape=size, dtype=dtype)
    tmp = helper.create_variable_for_type_inference(dtype)
    padding_idx = -1 if padding_idx is None else (padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    helper.append_op(
        type="lookup_table",
        inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [tmp]},
        attrs={"is_sparse": is_sparse, "is_distributed": is_distributed, "padding_idx": padding_idx},
    )
    return tmp


def conv2d(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups=1,
    param_attr=None,
    bias_attr=None,
    use_cudnn=True,
    act=None,
    name=None,
    data_format="NCHW",
):
    """reference: layers/nn.py conv2d (cuDNN dispatch dropped — XLA owns
    codegen).  ``data_format="NHWC"`` runs channels-last, the
    TPU-preferred activation layout."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(
            "conv2d data_format must be 'NCHW' or 'NHWC' (got %r)"
            % (data_format,)
        )
    helper = LayerHelper("conv2d", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name)
    num_channels = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    fsize = filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 2
    filter_shape = [num_filters, num_channels // groups] + list(fsize)
    from paddle_tpu import initializer

    fan_in = (num_channels // groups) * int(np.prod(fsize))
    std = (2.0 / fan_in) ** 0.5
    w = helper.create_parameter(
        param_attr,
        shape=filter_shape,
        dtype=input.dtype,
        default_initializer=initializer.Normal(0.0, std),
    )
    pre_bias = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="conv2d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": list(stride) if isinstance(stride, (list, tuple)) else [stride] * 2,
            "paddings": list(padding) if isinstance(padding, (list, tuple)) else [padding] * 2,
            "dilations": list(dilation) if isinstance(dilation, (list, tuple)) else [dilation] * 2,
            "groups": groups,
            "data_format": data_format,
        },
    )
    pre_act = _conv_bias(helper, pre_bias, data_format)
    return helper.append_activation(pre_act)


def _conv_bias(helper, pre_bias, data_format="NCHW"):
    bias_attr = helper.bias_attr
    if bias_attr is False:
        return pre_bias
    caxis = 1 if data_format == "NCHW" else len(pre_bias.shape) - 1
    num_filters = pre_bias.shape[caxis]
    b = helper.create_parameter(bias_attr, shape=[num_filters], dtype=pre_bias.dtype, is_bias=True)
    tmp = helper.create_variable_for_type_inference(pre_bias.dtype)
    helper.append_op(
        type="elementwise_add",
        inputs={"X": [pre_bias], "Y": [b]},
        outputs={"Out": [tmp]},
        attrs={"axis": caxis},
    )
    return tmp


def conv2d_transpose(
    input,
    num_filters,
    output_size=None,
    filter_size=None,
    stride=1,
    padding=0,
    dilation=1,
    groups=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    helper = LayerHelper("conv2d_transpose", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name)
    num_channels = input.shape[1]
    fsize = filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 2
    filter_shape = [num_channels, num_filters // groups] + list(fsize)
    w = helper.create_parameter(param_attr, shape=filter_shape, dtype=input.dtype)
    pre_bias = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="conv2d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": list(stride) if isinstance(stride, (list, tuple)) else [stride] * 2,
            "paddings": list(padding) if isinstance(padding, (list, tuple)) else [padding] * 2,
            "dilations": list(dilation) if isinstance(dilation, (list, tuple)) else [dilation] * 2,
            "groups": groups,
        },
    )
    pre_act = _conv_bias(helper, pre_bias)
    return helper.append_activation(pre_act)


def pool2d(
    input,
    pool_size=-1,
    pool_type="max",
    pool_stride=1,
    pool_padding=0,
    global_pooling=False,
    use_cudnn=True,
    ceil_mode=False,
    exclusive=True,
    name=None,
    data_format="NCHW",
):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": list(pool_size) if isinstance(pool_size, (list, tuple)) else [pool_size] * 2,
            "strides": list(pool_stride) if isinstance(pool_stride, (list, tuple)) else [pool_stride] * 2,
            "paddings": list(pool_padding) if isinstance(pool_padding, (list, tuple)) else [pool_padding] * 2,
            "global_pooling": global_pooling,
            "data_format": data_format,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        },
    )
    return out


def batch_norm(
    input,
    act=None,
    is_test=False,
    momentum=0.9,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    data_layout="NCHW",
    name=None,
    moving_mean_name=None,
    moving_variance_name=None,
    do_model_average_for_mean_and_var=False,
    use_global_stats=False,
    sync=False,
):
    """reference: layers/nn.py batch_norm.  Running stats are persistable
    vars updated in-graph (MeanOut/VarianceOut alias Mean/Variance)."""
    from paddle_tpu import initializer, unique_name
    from paddle_tpu.param_attr import ParamAttr

    helper = LayerHelper("batch_norm", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    dtype = input.dtype
    scale = helper.create_parameter(param_attr, shape=[c], dtype=dtype, default_initializer=initializer.Constant(1.0))
    bias = helper.create_parameter(bias_attr, shape=[c], dtype=dtype, is_bias=True)
    mean_name = moving_mean_name or unique_name.generate(helper.name + ".mean")
    var_name = moving_variance_name or unique_name.generate(helper.name + ".variance")
    block = helper.main_program.global_block()
    mean = block.create_var(name=mean_name, shape=[c], dtype=dtype, persistable=True, stop_gradient=True)
    variance = block.create_var(name=var_name, shape=[c], dtype=dtype, persistable=True, stop_gradient=True)
    helper.set_variable_initializer(mean, initializer.Constant(0.0))
    helper.set_variable_initializer(variance, initializer.Constant(1.0))
    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias], "Mean": [mean], "Variance": [variance]},
        outputs={
            "Y": [out],
            "MeanOut": [mean],
            "VarianceOut": [variance],
            "SavedMean": [saved_mean],
            "SavedVariance": [saved_var],
        },
        attrs={
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test or use_global_stats,
            "data_layout": data_layout,
            "sync_bn": bool(sync),
        },
    )
    return helper.append_activation(out)


def layer_norm(
    input,
    scale=True,
    shift=True,
    begin_norm_axis=1,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    from paddle_tpu import initializer

    helper = LayerHelper("layer_norm", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name)
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(param_attr, shape=norm_shape, dtype=input.dtype, default_initializer=initializer.Constant(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(bias_attr, shape=norm_shape, dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(input.dtype)
    mean = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        type="layer_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"epsilon": epsilon, "begin_norm_axis": begin_norm_axis},
    )
    return helper.append_activation(out)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None, act=None, name=None):
    from paddle_tpu import initializer

    helper = LayerHelper("group_norm", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name)
    c = input.shape[1]
    inputs = {"X": [input]}
    s = helper.create_parameter(param_attr, shape=[c], dtype=input.dtype, default_initializer=initializer.Constant(1.0))
    b = helper.create_parameter(bias_attr, shape=[c], dtype=input.dtype, is_bias=True)
    inputs["Scale"], inputs["Bias"] = [s], [b]
    out = helper.create_variable_for_type_inference(input.dtype)
    mean = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        type="group_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"epsilon": epsilon, "groups": groups},
    )
    return helper.append_activation(out)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None, dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="dropout",
        inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "seed": seed if seed is not None else helper.main_program.next_seed(),
            "dropout_implementation": dropout_implementation,
        },
    )
    return out


def _simple(op_type, x, attrs=None, out_slot="Out", in_slot="X", extra_outs=(), dtype=None):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(dtype or x.dtype)
    outputs = {out_slot: [out]}
    for slot in extra_outs:
        outputs[slot] = [helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)]
    helper.append_op(type=op_type, inputs={in_slot: [x]}, outputs=outputs, attrs=attrs or {})
    return out


def softmax(input, use_cudnn=False, name=None, axis=-1):
    return _simple("softmax", input, {"axis": axis})


def log_softmax(input, axis=-1, name=None):
    return _simple("log_softmax", input, {"axis": axis})


def relu(x, name=None):
    return _simple("relu", x)


def prelu(x, mode="all", param_attr=None, name=None):
    from paddle_tpu import initializer

    helper = LayerHelper("prelu", param_attr=param_attr, name=name)
    alpha_shape = [1] if mode == "all" else ([x.shape[1]] if mode == "channel" else list(x.shape[1:]))
    alpha = helper.create_parameter(param_attr, shape=alpha_shape, dtype=x.dtype, default_initializer=initializer.Constant(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="prelu", inputs={"X": [x], "Alpha": [alpha]}, outputs={"Out": [out]}, attrs={"mode": mode}
    )
    return out


def mean(x, name=None):
    return _simple("mean", x)


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="cross_entropy",
        inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def softmax_with_cross_entropy(
    logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True, return_softmax=False, axis=-1
):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax_out], "Loss": [loss]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index, "axis": axis},
    )
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None, normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sigmoid_cross_entropy_with_logits",
        inputs={"X": [x], "Label": [label]},
        outputs={"Out": [out]},
        attrs={"ignore_index": ignore_index, "normalize": normalize},
    )
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="square_error_cost", inputs={"X": [input], "Y": [label]}, outputs={"Out": [out]})
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    residual = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        type="huber_loss",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [out], "Residual": [residual]},
        attrs={"delta": delta},
    )
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    out = helper.create_variable_for_type_inference(x.dtype)
    diff = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="smooth_l1_loss",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out], "Diff": [diff]},
        attrs={"sigma": sigma or 1.0},
    )
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="log_loss",
        inputs={"Predicted": [input], "Labels": [label]},
        outputs={"Loss": [out]},
        attrs={"epsilon": epsilon},
    )
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32", name=None):
    from paddle_tpu.layers import tensor as ltensor

    k = label.shape[-1]
    smooth = ltensor.scale(label, scale=1.0 - epsilon)
    return ltensor.increment_const(smooth, epsilon / float(k))


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="matmul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y, "alpha": float(alpha)},
    )
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="mul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="l2_normalize",
        inputs={"X": [x]},
        outputs={"Out": [out], "Norm": [norm]},
        attrs={"axis": axis, "epsilon": epsilon},
    )
    return out


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(type="one_hot", inputs={"X": [input]}, outputs={"Out": [out]}, attrs={"depth": depth})
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    indices = helper.create_variable_for_type_inference("int64", stop_gradient=True)
    helper.append_op(
        type="top_k", inputs={"X": [input]}, outputs={"Out": [values], "Indices": [indices]}, attrs={"k": k}
    )
    return values, indices


def accuracy(input, label, k=1, correct=None, total=None):
    """reference: layers/metric_op.py accuracy — top_k + accuracy op."""
    helper = LayerHelper("accuracy")
    _, indices = topk(input, k)
    acc = helper.create_variable_for_type_inference("float32", stop_gradient=True)
    correct = correct or helper.create_variable_for_type_inference("int32", stop_gradient=True)
    total = total or helper.create_variable_for_type_inference("int32", stop_gradient=True)
    helper.append_op(
        type="accuracy",
        inputs={"Indices": [indices], "Label": [label]},
        outputs={"Accuracy": [acc], "Correct": [correct], "Total": [total]},
    )
    return acc


def auc(input, label, curve="ROC", num_thresholds=200, topk=1, slide_steps=1):
    # streaming AUC is provided by paddle_tpu.metrics.Auc; graph op variant
    # returns batch AUC approximation
    raise NotImplementedError("use paddle_tpu.metrics.Auc for streaming AUC")


def sequence_pool(input, pool_type, seq_len=None):
    helper = LayerHelper("sequence_pool")
    out = helper.create_variable_for_type_inference(input.dtype)
    midx = helper.create_variable_for_type_inference("int32", stop_gradient=True)
    inputs = {"X": [input]}
    if seq_len is None and input.block.has_var(input.name + "_seq_len"):
        seq_len = input.block.var(input.name + "_seq_len")
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op(
        type="sequence_pool",
        inputs=inputs,
        outputs={"Out": [out], "MaxIndex": [midx]},
        attrs={"pooltype": pool_type.upper()},
    )
    return out


def sequence_softmax(input, seq_len=None, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input]}
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op(type="sequence_softmax", inputs=inputs, outputs={"Out": [out]})
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="sequence_expand", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]})
    return out


def sequence_reverse(x, seq_len=None, name=None):
    helper = LayerHelper("sequence_reverse", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op(type="sequence_reverse", inputs=inputs, outputs={"Y": [out]})
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        type="sequence_mask",
        inputs={"X": [x]},
        outputs={"Y": [out]},
        attrs={"maxlen": maxlen if maxlen is not None else -1, "out_dtype": dtype},
    )
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    helper = LayerHelper("im2sequence", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="im2sequence",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "kernels": filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 2,
            "strides": stride if isinstance(stride, (list, tuple)) else [stride] * 2,
        },
    )
    return out


def maxout(x, groups, name=None):
    return _simple("maxout", x, {"groups": groups})


def pad(x, paddings, pad_value=0.0, name=None):
    return _simple("pad", x, {"paddings": paddings, "pad_value": pad_value})


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0, data_format="NCHW", name=None):
    return _simple("pad2d", input, {"paddings": paddings, "mode": mode, "pad_value": pad_value})


def clip(x, min, max, name=None):
    return _simple("clip", x, {"min": min, "max": max})


def clip_by_norm(x, max_norm, name=None):
    return _simple("clip_by_norm", x, {"max_norm": max_norm})


# ---------------------------------------------------------------------------
# round-2 breadth: CTC, sequence_conv, NCE, hsigmoid, cell units, resize,
# pixel ops, py_func (reference: layers/nn.py warpctc:4324, nce:4950,
# hsigmoid:5066, sequence_conv:2210, image_resize:7622, pixel_shuffle,
# py_func)
# ---------------------------------------------------------------------------
def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None):
    """CTC loss; input [B, T, C] padded logits, label [B, L]."""
    helper = LayerHelper("warpctc")
    loss = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Logits": [input], "Label": [label]}
    if input_length is not None:
        ins["LogitsLength"] = [input_length]
    if label_length is not None:
        ins["LabelLength"] = [label_length]
    helper.append_op(
        type="warpctc", inputs=ins, outputs={"Loss": [loss]},
        attrs={"blank": blank, "norm_by_times": norm_by_times},
    )
    return loss


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, bias_attr=None, param_attr=None, act=None,
                  seq_len=None, name=None):
    """Context-window conv over padded sequences [B, T, D]."""
    helper = LayerHelper("sequence_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    D = input.shape[-1]
    w = helper.create_parameter(param_attr, shape=[filter_size * D, num_filters],
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input], "Filter": [w]}
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op(
        type="sequence_conv", inputs=ins, outputs={"Out": [out]},
        attrs={"contextStart": -int(filter_size // 2), "contextLength": filter_size,
               "contextStride": filter_stride},
    )
    return helper.append_activation(helper.append_bias_op(out, dim_start=2))


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=10, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss -> [B, 1] cost.  uniform,
    log_uniform (Zipfian), and custom_dist (a length-num_total_classes
    probability sequence — the reference's CustomSampler,
    operators/math/sampler.cc) samplers with their log(k*P) corrections;
    ``sample_weight`` [B, 1] scales each example's cost
    (reference: operators/nce_op.h sample_weight)."""
    if custom_dist is not None:
        sampler = "custom_dist"
    if sampler not in ("uniform", "log_uniform", "custom_dist"):
        raise ValueError("nce: unknown sampler %r" % sampler)
    if sampler == "custom_dist" and custom_dist is None:
        raise ValueError("nce: sampler='custom_dist' requires custom_dist")
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr, name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(param_attr, shape=[num_total_classes, dim], dtype=input.dtype)
    b = helper.create_parameter(bias_attr, shape=[num_total_classes], dtype=input.dtype, is_bias=True)
    cost = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Input": [input], "Label": [label], "Weight": [w]}
    if b is not None:
        ins["Bias"] = [b]
    if sample_weight is not None:
        ins["SampleWeight"] = [sample_weight]
    attrs = {"num_neg_samples": num_neg_samples, "seed": seed, "sampler": sampler}
    if custom_dist is not None:
        import numpy as _np

        dist = _np.asarray(custom_dist, dtype=_np.float32).reshape(-1)
        if dist.shape[0] != num_total_classes:
            raise ValueError(
                "nce: custom_dist length %d != num_total_classes %d"
                % (dist.shape[0], num_total_classes)
            )
        attrs["custom_dist"] = dist
    helper.append_op(type="nce", inputs=ins, outputs={"Cost": [cost]}, attrs=attrs)
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """Hierarchical sigmoid loss.  Default: complete binary tree over
    ``num_classes`` leaves.  Custom (is_custom=True): ``path_table`` /
    ``path_code`` [N, L] give each sample's leaf->root non-leaf indices
    (-1 padded) and branch labels, and ``num_classes`` is the NON-LEAF
    count (reference: layers/nn.py hsigmoid custom-tree contract)."""
    if is_custom:
        if path_table is None or path_code is None:
            raise ValueError(
                "hsigmoid(is_custom=True) requires path_table and path_code"
            )
    elif path_table is not None or path_code is not None:
        raise ValueError(
            "hsigmoid: path_table/path_code need is_custom=True "
            "(silently ignoring them would train the wrong tree)"
        )
    helper = LayerHelper("hierarchical_sigmoid", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dim = input.shape[-1]
    rows = num_classes if is_custom else num_classes - 1
    w = helper.create_parameter(param_attr, shape=[rows, dim], dtype=input.dtype)
    b = helper.create_parameter(bias_attr, shape=[rows], dtype=input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    pre = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input], "Label": [label], "W": [w]}
    if b is not None:
        ins["Bias"] = [b]
    if is_custom:
        ins["PathTable"] = [path_table]
        ins["PathCode"] = [path_code]
    helper.append_op(
        type="hierarchical_sigmoid", inputs=ins,
        outputs={"Out": [out], "PreOut": [pre]},
        attrs={"num_classes": num_classes, "is_custom": is_custom},
    )
    return out


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """One LSTM step: returns (hidden, cell).  x_t [B, D] concatenated
    with h_prev feeds a 4H projection (reference: layers/nn.py lstm_unit)."""
    helper = LayerHelper("lstm_unit", param_attr=param_attr, bias_attr=bias_attr, name=name)
    from paddle_tpu.layers import tensor as ltensor

    H = hidden_t_prev.shape[-1]
    cat = ltensor.concat([x_t, hidden_t_prev], axis=1)
    gates = fc(cat, 4 * H, param_attr=param_attr, bias_attr=bias_attr)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op(
        type="lstm_unit",
        inputs={"X": [gates], "C_prev": [cell_t_prev]},
        outputs={"C": [c], "H": [h]},
        attrs={"forget_bias": forget_bias},
    )
    return h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid", origin_mode=False):
    """One GRU step (reference: layers/nn.py gru_unit).  size = 3*H."""
    helper = LayerHelper("gru_unit", param_attr=param_attr, bias_attr=bias_attr)
    H = size // 3
    w = helper.create_parameter(param_attr, shape=[H, 3 * H], dtype=input.dtype)
    b = helper.create_parameter(bias_attr, shape=[1, 3 * H], dtype=input.dtype, is_bias=True)
    gate = helper.create_variable_for_type_inference(input.dtype)
    reset_h = helper.create_variable_for_type_inference(input.dtype)
    out_h = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Input": [input], "HiddenPrev": [hidden], "Weight": [w]}
    if b is not None:
        ins["Bias"] = [b]
    helper.append_op(
        type="gru_unit", inputs=ins,
        outputs={"Gate": [gate], "ResetHiddenPrev": [reset_h], "Hidden": [out_h]},
        attrs={},
    )
    return out_h, reset_h, gate


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1):
    """reference: layers/nn.py image_resize — bilinear/nearest."""
    helper = LayerHelper("image_resize", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    if out_shape is None and scale is None:
        raise ValueError("image_resize: one of out_shape and scale must be set")
    attrs = {"align_corners": bool(align_corners)}
    if out_shape is not None:
        attrs["out_h"], attrs["out_w"] = int(out_shape[0]), int(out_shape[1])
    if scale is not None:
        attrs["scale"] = float(scale)
    op_type = "bilinear_interp" if resample.upper() == "BILINEAR" else "nearest_interp"
    helper.append_op(type=op_type, inputs={"X": [input]}, outputs={"Out": [out]}, attrs=attrs)
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    align_corners=True, align_mode=1, **kw):
    return image_resize(input, out_shape, scale, name, "BILINEAR",
                        align_corners=align_corners, align_mode=align_mode)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   align_corners=True, **kw):
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        align_corners=align_corners)


def pixel_shuffle(x, upscale_factor):
    helper = LayerHelper("pixel_shuffle")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="pixel_shuffle", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"upscale_factor": upscale_factor})
    return out


def shuffle_channel(x, group):
    helper = LayerHelper("shuffle_channel")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="shuffle_channel", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"group": group})
    return out


def crop(x, shape=None, offsets=None, name=None):
    helper = LayerHelper("crop", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": [x]}
    attrs = {"offsets": list(offsets or [0] * len(x.shape))}
    if isinstance(shape, (list, tuple)):
        attrs["shape"] = list(shape)
    elif shape is not None:
        ins["Y"] = [shape]
    helper.append_op(type="crop", inputs=ins, outputs={"Out": [out]}, attrs=attrs)
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    helper = LayerHelper("pad_constant_like", name=name)
    out = helper.create_variable_for_type_inference(y.dtype)
    helper.append_op(type="pad_constant_like", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"pad_value": float(pad_value)})
    return out


# py_func escape hatch (reference: operators/py_func_op.cc + layers
# py_func).  The registry dedupes identical (func, specs) registrations
# so rebuilding the same program in a loop doesn't grow it; distinct
# closures (e.g. fresh lambdas per rebuild) are pinned for the process
# lifetime — reuse a module-level function for long loops.
_PY_FUNC_REGISTRY = []
_PY_FUNC_INDEX = {}


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None,
            out_shape_fn=None):
    """Run a host-python ``func`` inside the compiled step via
    jax.pure_callback.  ``out`` must be pre-created var(s) with correct
    shape/dtype (reference contract).  backward_func is not supported —
    mark inputs stop_gradient or use differentiable ops.

    Dynamic out dims: a ``-1`` in position 0 resolves from the first
    input's leading (batch) dim; any other dynamic dim needs
    ``out_shape_fn(input_shapes) -> [shape, ...]``, called at trace time
    with the actual input shapes."""
    if backward_func is not None:
        raise NotImplementedError("py_func backward_func: use differentiable ops")
    helper = LayerHelper("py_func")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    from paddle_tpu.core import types as core_types

    specs = [(tuple(int(s) for s in o.shape), core_types.np_dtype(o.dtype)) for o in outs]
    dedupe_key = (func, tuple(specs), out_shape_fn)
    func_id = _PY_FUNC_INDEX.get(dedupe_key)
    if func_id is None:
        _PY_FUNC_REGISTRY.append((func, specs, out_shape_fn))
        func_id = len(_PY_FUNC_REGISTRY) - 1
        _PY_FUNC_INDEX[dedupe_key] = func_id
    helper.append_op(
        type="py_func",
        inputs={"X": [v.name for v in xs]},
        outputs={"Out": [o.name for o in outs]},
        attrs={"func_id": func_id},
    )
    return outs if isinstance(out, (list, tuple)) else outs[0]


def sequence_erase(input, tokens, seq_len=None, name=None):
    """reference: sequence_erase_op.cc; returns (packed, new_seq_len)."""
    helper = LayerHelper("sequence_erase", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    new_len = helper.create_variable_for_type_inference("int32")
    ins = {"X": [input]}
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op(type="sequence_erase", inputs=ins,
                     outputs={"Out": [out], "OutSeqLen": [new_len]},
                     attrs={"tokens": list(tokens)})
    return out, new_len


def sequence_enumerate(input, win_size, pad_value=0, seq_len=None, name=None):
    helper = LayerHelper("sequence_enumerate", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input]}
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op(type="sequence_enumerate", inputs=ins, outputs={"Out": [out]},
                     attrs={"win_size": win_size, "pad_value": pad_value})
    return out


def sequence_expand_as(x, y, seq_len=None, name=None):
    helper = LayerHelper("sequence_expand_as", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="sequence_expand_as", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={})
    return out


def sequence_first_step(input, seq_len=None):
    return sequence_pool(input, "first", seq_len=seq_len)


def sequence_last_step(input, seq_len=None):
    return sequence_pool(input, "last", seq_len=seq_len)


# ---------------------------------------------------------------------------
# CRF, spectral/data norm, row_conv, bilinear tensor product, edit distance
# (reference: layers/nn.py linear_chain_crf:1358, crf_decoding:1419,
# data_norm:3353, spectral_norm:3670, edit_distance:5459, row_conv:6334,
# bilinear_tensor_product:11534)
# ---------------------------------------------------------------------------
def linear_chain_crf(input, label, param_attr=None, seq_len=None):
    """CRF negative log-likelihood cost [B, 1]; creates the [K+2, K]
    transition parameter (row 0 start, row 1 end, rows 2.. transitions)."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    size = input.shape[-1]
    transition = helper.create_parameter(param_attr, shape=[size + 2, size], dtype=input.dtype)
    alpha = helper.create_variable_for_type_inference(input.dtype)
    emission_exps = helper.create_variable_for_type_inference(input.dtype)
    transition_exps = helper.create_variable_for_type_inference(input.dtype)
    log_likelihood = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Emission": [input], "Transition": [transition], "Label": [label]}
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op(
        type="linear_chain_crf", inputs=ins,
        outputs={"Alpha": [alpha], "EmissionExps": [emission_exps],
                 "TransitionExps": [transition_exps],
                 "LogLikelihood": [log_likelihood]},
        attrs={},
    )
    return log_likelihood


def crf_decoding(input, param_attr, label=None, seq_len=None):
    """Viterbi decode using the transition parameter created by
    linear_chain_crf (shared by ``param_attr.name``)."""
    from paddle_tpu.param_attr import ParamAttr

    helper = LayerHelper("crf_decoding")
    attr = ParamAttr._to_attr(param_attr)
    transition = helper.main_program.global_block().var(attr.name)
    viterbi_path = helper.create_variable_for_type_inference("int64")
    ins = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        ins["Label"] = [label]
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op(type="crf_decoding", inputs=ins,
                     outputs={"ViterbiPath": [viterbi_path]}, attrs={})
    return viterbi_path


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Spectrally-normalized view of ``weight``; creates persistent U/V
    power-iteration buffers (Normal-initialized, non-trainable)."""
    from paddle_tpu import initializer
    from paddle_tpu.param_attr import ParamAttr

    helper = LayerHelper("spectral_norm", name=name)
    if any(int(s) < 0 for s in weight.shape):
        raise ValueError(
            "spectral_norm requires a fully static weight shape, got %s"
            % (weight.shape,)
        )
    h = int(weight.shape[dim])
    w = 1
    for i, s in enumerate(weight.shape):
        if i != dim:
            w *= int(s)
    u = helper.create_parameter(
        ParamAttr(trainable=False), shape=[h], dtype=weight.dtype,
        default_initializer=initializer.Normal(0.0, 1.0))
    v = helper.create_parameter(
        ParamAttr(trainable=False), shape=[w], dtype=weight.dtype,
        default_initializer=initializer.Normal(0.0, 1.0))
    out = helper.create_variable_for_type_inference(weight.dtype)
    helper.append_op(
        type="spectral_norm",
        inputs={"Weight": [weight], "U": [u], "V": [v]},
        outputs={"Out": [out]},
        attrs={"dim": int(dim), "power_iters": int(power_iters), "eps": float(eps)},
    )
    return out


def data_norm(input, act=None, epsilon=1e-4, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=False):
    """CTR data normalization; BatchSize/BatchSum/BatchSquareSum stat
    accumulators are *trainable* so SGD folds fresh batch stats in via
    the op's custom cotangents (see ops/nn_ops.py data_norm)."""
    from paddle_tpu import initializer
    from paddle_tpu.param_attr import ParamAttr

    helper = LayerHelper("data_norm", name=name, act=act)
    c = int(input.shape[1] if data_layout == "NCHW" else input.shape[-1])
    defaults = {"batch_size": 1e4, "batch_sum": 0.0, "batch_square": 1e4}
    if param_attr and isinstance(param_attr, dict):
        defaults.update({k: param_attr.get(k, v) for k, v in defaults.items()})
    mk = lambda suffix, val: helper.create_parameter(
        ParamAttr(name=None if name is None else name + "." + suffix),
        shape=[c], dtype=input.dtype,
        default_initializer=initializer.Constant(float(val)))
    batch_size = mk("batch_size", defaults["batch_size"])
    batch_sum = mk("batch_sum", defaults["batch_sum"])
    batch_square_sum = mk("batch_square_sum", defaults["batch_square"])
    means = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    scales = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="data_norm",
        inputs={"X": [input], "BatchSize": [batch_size],
                "BatchSum": [batch_sum], "BatchSquareSum": [batch_square_sum]},
        outputs={"Y": [out], "Means": [means], "Scales": [scales]},
        attrs={"epsilon": float(epsilon), "data_layout": data_layout},
    )
    return helper.append_activation(out)


def row_conv(input, future_context_size, param_attr=None, act=None, seq_len=None):
    """Lookahead (row) convolution; filter [future_context_size + 1, D]."""
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act)
    d = int(input.shape[-1])
    filt = helper.create_parameter(param_attr, shape=[future_context_size + 1, d],
                                   dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input], "Filter": [filt]}
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
    helper.append_op(type="row_conv", inputs=ins, outputs={"Out": [out]}, attrs={})
    return helper.append_activation(out)


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    """out[b, k] = x[b]^T W[k] y[b] + bias, W [size, M, N]."""
    helper = LayerHelper("bilinear_tensor_product", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    m, n = int(x.shape[-1]), int(y.shape[-1])
    w = helper.create_parameter(param_attr, shape=[size, m, n], dtype=x.dtype)
    bias = helper.create_parameter(bias_attr, shape=[1, size], dtype=x.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": [x], "Y": [y], "Weight": [w]}
    if bias is not None:
        ins["Bias"] = [bias]
    helper.append_op(type="bilinear_tensor_product", inputs=ins,
                     outputs={"Out": [out]}, attrs={})
    return helper.append_activation(out)


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """Batched Levenshtein distance -> (Out [B, 1], SequenceNum []).
    ``ignored_tokens`` are erased (sequence_erase) before the DP."""
    helper = LayerHelper("edit_distance")
    if ignored_tokens:
        input, input_length = sequence_erase(input, ignored_tokens, input_length)
        label, label_length = sequence_erase(label, ignored_tokens, label_length)
    out = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64")
    ins = {"Hyps": [input], "Refs": [label]}
    if input_length is not None:
        ins["HypsLength"] = [input_length]
    if label_length is not None:
        ins["RefsLength"] = [label_length]
    helper.append_op(type="edit_distance", inputs=ins,
                     outputs={"Out": [out], "SequenceNum": [seq_num]},
                     attrs={"normalized": normalized})
    return out, seq_num


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0):
    """Greedy CTC decode: per-step argmax then ctc_align (merge repeats,
    drop blanks).  Returns (decoded [B, T], decoded_length [B])."""
    helper = LayerHelper("ctc_greedy_decoder")
    from paddle_tpu.layers import tensor as ltensor

    idx = ltensor.argmax(input, axis=-1)
    out = helper.create_variable_for_type_inference("int64")
    out_len = helper.create_variable_for_type_inference("int32")
    ins = {"Input": [idx]}
    if input_length is not None:
        ins["SeqLen"] = [input_length]
    helper.append_op(type="ctc_align", inputs=ins,
                     outputs={"Output": [out], "OutputLength": [out_len]},
                     attrs={"blank": int(blank), "merge_repeated": True,
                            "padding_num": int(padding_value)})
    return out, out_len


def nested_sequence_pool(input, outer_len, inner_len, pool_type="sum",
                         inner_pool_type=None):
    """N-level LoD pooling on the padded nested encoding (reference:
    nested-sequence semantics of lod_tensor.h:110,:229 — recursively
    nested sequences, e.g. doc -> sentence -> word).

    ``inner_len`` is one length tensor (2-level) or a list ordered
    outer->inner (N-level): level k's tensor has shape [B, S1..Sk].
    For input [B, S1, ..., SL, D...], pools the innermost level with
    ``inner_pool_type`` (defaults to ``pool_type``), then each enclosing
    level with ``pool_type``; returns [B, D...].  Each level is a
    flatten-to-[prod, Sk, D] + standard masked sequence_pool — the
    static-shape equivalent of the reference's per-level LoD walk."""
    from paddle_tpu.layers import tensor as ltensor

    inners = list(inner_len) if isinstance(inner_len, (list, tuple)) else [inner_len]
    lengths = [outer_len] + inners  # index k = level-k lengths, [B, S1..Sk]
    L = len(lengths)
    x = input
    for k in range(L, 0, -1):
        tail = [int(s) for s in x.shape[k:]]  # [Sk, D...]
        flat = ltensor.reshape(x, shape=[-1] + tail)
        ln = lengths[k - 1]
        ln_flat = ltensor.reshape(ln, shape=[-1]) if k > 1 else ln
        ptype = (inner_pool_type or pool_type) if k == L else pool_type
        pooled = sequence_pool(flat, ptype, seq_len=ln_flat)  # [prod, D...]
        if k > 1:
            lead = [int(s) for s in input.shape[1:k]]
            x = ltensor.reshape(
                pooled, shape=[-1] + lead + [int(s) for s in pooled.shape[1:]]
            )
        else:
            x = pooled
    return x
