"""Recurrent layers: dynamic_lstm / dynamic_gru / lstm / gru_unit.

Reference: python/paddle/fluid/layers/nn.py dynamic_lstm:519,
dynamic_gru, lstm (cudnn_lstm).  LoD ragged inputs become padded+length
pairs (layers/io.py data(lod_level=1)); the scan kernels mask padding so
numerics match the reference's ragged batching.
"""
from __future__ import annotations

from paddle_tpu.layer_helper import LayerHelper

__all__ = ["dynamic_lstm", "dynamic_gru"]


def _seq_len_of(helper, input, seq_len):
    if seq_len is not None:
        return seq_len
    blk = input.block
    cand = input.name + "_seq_len"
    if blk.has_var(cand):
        return blk.var(cand)
    return None


def dynamic_lstm(
    input,
    size,
    h_0=None,
    c_0=None,
    param_attr=None,
    bias_attr=None,
    use_peepholes=True,
    is_reverse=False,
    gate_activation="sigmoid",
    cell_activation="tanh",
    candidate_activation="tanh",
    dtype="float32",
    name=None,
    seq_len=None,
):
    """reference: layers/nn.py:519.  ``input`` [B, T, 4*size//4] must be
    pre-projected to 4 gates (same contract as the reference).  Returns
    (hidden [B,T,D], cell [B,T,D])."""
    helper = LayerHelper("dynamic_lstm", param_attr=param_attr, bias_attr=bias_attr, name=name)
    D = size // 4
    w = helper.create_parameter(param_attr, shape=[D, 4 * D], dtype=dtype)
    bias_size = 4 * D + (3 * D if use_peepholes else 0)
    b = helper.create_parameter(bias_attr, shape=[1, bias_size], dtype=dtype, is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [w], "Bias": [b]}
    sl = _seq_len_of(helper, input, seq_len)
    if sl is not None:
        inputs["SeqLen"] = [sl]
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(
        type="dynamic_lstm",
        inputs=inputs,
        outputs={"Hidden": [hidden], "Cell": [cell]},
        attrs={
            "use_peepholes": use_peepholes,
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
        },
    )
    return hidden, cell


def dynamic_gru(
    input,
    size,
    param_attr=None,
    bias_attr=None,
    is_reverse=False,
    gate_activation="sigmoid",
    candidate_activation="tanh",
    h_0=None,
    dtype="float32",
    name=None,
    seq_len=None,
):
    """reference: layers/nn.py dynamic_gru.  ``input`` [B, T, 3*size]
    pre-projected; returns hidden [B, T, size]."""
    helper = LayerHelper("dynamic_gru", param_attr=param_attr, bias_attr=bias_attr, name=name)
    w = helper.create_parameter(param_attr, shape=[size, 3 * size], dtype=dtype)
    b = helper.create_parameter(bias_attr, shape=[1, 3 * size], dtype=dtype, is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [w], "Bias": [b]}
    sl = _seq_len_of(helper, input, seq_len)
    if sl is not None:
        inputs["SeqLen"] = [sl]
    if h_0 is not None:
        inputs["H0"] = [h_0]
    helper.append_op(
        type="dynamic_gru",
        inputs=inputs,
        outputs={"Hidden": [hidden]},
        attrs={
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "activation": candidate_activation,
        },
    )
    return hidden
