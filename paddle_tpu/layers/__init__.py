"""fluid-style layers namespace (reference: python/paddle/fluid/layers/)."""
from paddle_tpu.layers import control_flow, detection, extended, io, learning_rate_scheduler, nn, ops, rnn, tensor
from paddle_tpu.layers.control_flow import *  # noqa: F401,F403
from paddle_tpu.layers.detection import *  # noqa: F401,F403
from paddle_tpu.layers.extended import *  # noqa: F401,F403
from paddle_tpu.layers.io import *  # noqa: F401,F403
from paddle_tpu.layers.learning_rate_scheduler import *  # noqa: F401,F403
from paddle_tpu.layers.nn import *  # noqa: F401,F403
from paddle_tpu.layers.ops import *  # noqa: F401,F403
from paddle_tpu.layers.rnn import *  # noqa: F401,F403
from paddle_tpu.layers.tensor import *  # noqa: F401,F403
