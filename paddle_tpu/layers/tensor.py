"""Tensor layers (reference: python/paddle/fluid/layers/tensor.py)."""
from __future__ import annotations

import builtins

import numpy as np

from paddle_tpu import framework
from paddle_tpu.core import types as core_types
from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "create_parameter",
    "create_tensor",
    "create_global_var",
    "cast",
    "concat",
    "split",
    "sums",
    "assign",
    "fill_constant",
    "fill_constant_batch_size_like",
    "ones",
    "zeros",
    "ones_like",
    "zeros_like",
    "reshape",
    "transpose",
    "squeeze",
    "unsqueeze",
    "flatten",
    "stack",
    "unstack",
    "expand",
    "slice",
    "scale",
    "increment_const",
    "reduce_sum",
    "reduce_mean",
    "reduce_max",
    "reduce_min",
    "reduce_prod",
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "elementwise_max",
    "elementwise_min",
    "elementwise_pow",
    "equal",
    "not_equal",
    "less_than",
    "greater_than",
    "less_equal",
    "greater_equal",
    "logical_and",
    "logical_or",
    "logical_not",
    "argmax",
    "argmin",
    "argsort",
    "gather",
    "scatter",
    "where",
    "shape",
    "range",
    "cumsum",
    "isfinite",
    "pow",
]


def _helper_out(op_type, inputs, attrs=None, dtype="float32", out_slot="Out", stop_gradient=False, extra=None):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(dtype, stop_gradient=stop_gradient)
    outputs = {out_slot: [out]}
    if extra:
        outputs.update(extra(helper))
    helper.append_op(type=op_type, inputs=inputs, outputs=outputs, attrs=attrs or {})
    return out


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """reference: layers/tensor.py create_parameter — a raw trainable
    parameter outside any layer."""
    from paddle_tpu import initializer as init_mod
    from paddle_tpu.param_attr import ParamAttr

    helper = LayerHelper("create_parameter")
    attr = attr or ParamAttr(name=name)
    if default_initializer is None:
        default_initializer = (
            init_mod.Constant(0.0) if is_bias else init_mod.Xavier()
        )
    return helper.create_parameter(attr, shape, dtype, is_bias, default_initializer)


def create_tensor(dtype, name=None, persistable=False):
    block = framework.default_main_program().current_block()
    from paddle_tpu import unique_name

    return block.create_var(
        name=name or unique_name.generate("create_tensor"),
        dtype=core_types.canonical_dtype(dtype),
        persistable=persistable,
    )


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False, name=None):
    from paddle_tpu import initializer, unique_name

    helper = LayerHelper("global_var")
    name = name or unique_name.generate("global_var")
    block = framework.default_main_program().global_block()
    var = block.create_var(
        name=name, shape=shape, dtype=core_types.canonical_dtype(dtype), persistable=persistable, stop_gradient=True
    )
    helper.set_variable_initializer(var, initializer.Constant(value))
    return var


def cast(x, dtype):
    dtype = core_types.canonical_dtype(dtype)
    return _helper_out("cast", {"X": [x]}, {"in_dtype": x.dtype, "out_dtype": dtype}, dtype=dtype)


def concat(input, axis=0, name=None):
    return _helper_out("concat", {"X": list(input)}, {"axis": axis}, dtype=input[0].dtype)


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    dim = dim if dim >= 0 else len(input.shape) + dim
    if isinstance(num_or_sections, int):
        num = num_or_sections
        attrs = {"num": num, "axis": dim, "sections": []}
        n_out = num
    else:
        attrs = {"num": 0, "axis": dim, "sections": list(num_or_sections)}
        n_out = len(num_or_sections)
    outs = [helper.create_variable_for_type_inference(input.dtype) for _ in builtins.range(n_out)]
    helper.append_op(type="split", inputs={"X": [input]}, outputs={"Out": outs}, attrs=attrs)
    return outs


def sums(input, out=None):
    helper = LayerHelper("sum")
    out = out or helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(type="sum", inputs={"X": list(input)}, outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, np.ndarray):
        out = output or helper.create_variable_for_type_inference(str(input.dtype))
        helper.append_op(
            type="assign_value",
            outputs={"Out": [out]},
            attrs={"shape": list(input.shape), "dtype": str(input.dtype), "values": input.flatten().tolist()},
        )
        return out
    out = output or helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="assign", inputs={"X": [input]}, outputs={"Out": [out]})
    return out


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    dtype = core_types.canonical_dtype(dtype)
    out = out or helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        type="fill_constant",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": dtype, "value": float(value)},
    )
    return out


def fill_constant_batch_size_like(input, shape, dtype, value, input_dim_idx=0, output_dim_idx=0):
    dtype = core_types.canonical_dtype(dtype)
    return _helper_out(
        "fill_constant_batch_size_like",
        {"Input": [input]},
        {
            "shape": list(shape),
            "dtype": dtype,
            "value": float(value),
            "input_dim_idx": input_dim_idx,
            "output_dim_idx": output_dim_idx,
        },
        dtype=dtype,
        stop_gradient=True,
    )


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def zeros_like(x, out=None):
    helper = LayerHelper("fill_zeros_like")
    out = out or helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(type="fill_zeros_like", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def ones_like(x, out=None):
    return fill_constant_batch_size_like(x, list(x.shape), x.dtype, 1.0)


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="reshape2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"shape": list(shape)},
    )
    if act:
        helper.kwargs["act"] = act
        return helper.append_activation(out)
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="transpose2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axis": list(perm)},
    )
    return out


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        type="squeeze2", inputs={"X": [input]}, outputs={"Out": [out], "XShape": [xshape]}, attrs={"axes": axes}
    )
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        type="unsqueeze2", inputs={"X": [input]}, outputs={"Out": [out], "XShape": [xshape]}, attrs={"axes": axes}
    )
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="flatten2", inputs={"X": [x]}, outputs={"Out": [out], "XShape": [xshape]}, attrs={"axis": axis}
    )
    return out


def stack(x, axis=0):
    return _helper_out("stack", {"X": list(x)}, {"axis": axis}, dtype=x[0].dtype, out_slot="Y")


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    num = num or x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype) for _ in builtins.range(num)]
    helper.append_op(type="unstack", inputs={"X": [x]}, outputs={"Y": outs}, attrs={"axis": axis, "num": num})
    return outs


def expand(x, expand_times, name=None):
    return _helper_out("expand", {"X": [x]}, {"expand_times": expand_times}, dtype=x.dtype)


def slice(input, axes, starts, ends):
    return _helper_out(
        "slice", {"Input": [input]}, {"axes": axes, "starts": starts, "ends": ends}, dtype=input.dtype
    )


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", name=name, act=act)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="scale",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"scale": float(scale), "bias": float(bias), "bias_after_scale": bias_after_scale},
    )
    return helper.append_activation(out)


def increment_const(x, value):
    return scale(x, scale=1.0, bias=float(value))


def _reduce(op_type, input, dim, keep_dim, name=None):
    attrs = {"keep_dim": keep_dim, "reduce_all": dim is None}
    if dim is not None:
        attrs["dim"] = dim if isinstance(dim, (list, tuple)) else [dim]
    else:
        attrs["dim"] = [0]
    return _helper_out(op_type, {"X": [input]}, attrs, dtype=input.dtype)


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim, name)


def _elementwise(op_type, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op_type, name=name, act=act)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]}, attrs={"axis": axis})
    return helper.append_activation(out)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_div", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_max", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_min", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_pow", x, y, axis, act, name)


def _compare(op_type, x, y, cond=None):
    helper = LayerHelper(op_type)
    cond = cond or helper.create_variable_for_type_inference("bool", stop_gradient=True)
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]}, outputs={"Out": [cond]})
    return cond


def equal(x, y, cond=None):
    return _compare("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _compare("not_equal", x, y, cond)


def less_than(x, y, cond=None, force_cpu=None):
    return _compare("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _compare("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _compare("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _compare("greater_equal", x, y, cond)


def logical_and(x, y, out=None, name=None):
    return _compare("logical_and", x, y, out)


def logical_or(x, y, out=None, name=None):
    return _compare("logical_or", x, y, out)


def logical_not(x, out=None, name=None):
    helper = LayerHelper("logical_not")
    out = out or helper.create_variable_for_type_inference("bool", stop_gradient=True)
    helper.append_op(type="logical_not", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def argmax(x, axis=0):
    return _helper_out("arg_max", {"X": [x]}, {"axis": axis}, dtype="int64", stop_gradient=True)


def argmin(x, axis=0):
    return _helper_out("arg_min", {"X": [x]}, {"axis": axis}, dtype="int64", stop_gradient=True)


def argsort(input, axis=-1, descending=False, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    ids = helper.create_variable_for_type_inference("int64", stop_gradient=True)
    helper.append_op(
        type="argsort",
        inputs={"X": [input]},
        outputs={"Out": [out], "Indices": [ids]},
        attrs={"axis": axis, "descending": descending},
    )
    return out, ids


def gather(input, index):
    return _helper_out("gather", {"X": [input], "Index": [index]}, dtype=input.dtype)


def scatter(input, index, updates, name=None, overwrite=True):
    return _helper_out(
        "scatter", {"X": [input], "Ids": [index], "Updates": [updates]}, {"overwrite": overwrite}, dtype=input.dtype
    )


def where(condition, x, y):
    return _helper_out("where", {"Condition": [condition], "X": [x], "Y": [y]}, dtype=x.dtype)


def shape(input):
    return _helper_out("shape", {"Input": [input]}, dtype="int32", stop_gradient=True)


def range(start, end, step, dtype):
    dtype = core_types.canonical_dtype(dtype)
    return _helper_out(
        "range", {}, {"start": float(start), "end": float(end), "step": float(step), "dtype": dtype},
        dtype=dtype, stop_gradient=True,
    )


def cumsum(x, axis=None, exclusive=None, reverse=None):
    attrs = {}
    if axis is not None:
        attrs["axis"] = axis
    if exclusive is not None:
        attrs["exclusive"] = exclusive
    if reverse is not None:
        attrs["reverse"] = reverse
    return _helper_out("cumsum", {"X": [x]}, attrs, dtype=x.dtype)


def isfinite(x):
    return _helper_out("isfinite", {"X": [x]}, dtype="bool", stop_gradient=True)


def pow(x, factor=1.0, name=None):
    return _helper_out("pow", {"X": [x]}, {"factor": float(factor)}, dtype=x.dtype)
