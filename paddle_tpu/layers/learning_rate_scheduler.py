"""In-graph LR schedules (reference: python/paddle/fluid/layers/
learning_rate_scheduler.py — noam/exponential/natural_exp/inverse_time/
polynomial/piecewise/cosine decay + linear warmup).

Each schedule creates a persistable global step counter incremented
in-graph and computes the LR as part of the compiled step — no host
round-trip per step.
"""
from __future__ import annotations

import math

from paddle_tpu import framework, unique_name
from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "noam_decay",
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
    "cosine_decay",
    "linear_lr_warmup",
]


def _decay_step_counter(begin=0):
    from paddle_tpu import initializer
    from paddle_tpu.layers import tensor as ltensor

    helper = LayerHelper("global_step_counter")
    counter = framework.default_main_program().global_block().create_var(
        name=unique_name.generate("@LR_DECAY_COUNTER@"),
        shape=[1],
        dtype="float32",
        persistable=True,
        stop_gradient=True,
    )
    helper.set_variable_initializer(counter, initializer.Constant(float(begin - 1)))
    helper.append_op(
        type="scale",
        inputs={"X": [counter]},
        outputs={"Out": [counter]},
        attrs={"scale": 1.0, "bias": 1.0},
    )
    return counter


def noam_decay(d_model, warmup_steps):
    from paddle_tpu.layers import ops as lops
    from paddle_tpu.layers import tensor as lt

    step = _decay_step_counter(1)
    a = lops.rsqrt(step)
    b = lt.scale(step, scale=float(warmup_steps) ** -1.5)
    lr = lt.elementwise_min(a, b)
    return lt.scale(lr, scale=float(d_model) ** -0.5)


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    from paddle_tpu.layers import ops as lops
    from paddle_tpu.layers import tensor as lt

    step = _decay_step_counter()
    div = lt.scale(step, scale=1.0 / float(decay_steps))
    if staircase:
        div = lops.floor(div)
    factor = lt.elementwise_pow(
        lt.fill_constant([1], "float32", decay_rate), div
    )
    return lt.scale(factor, scale=float(learning_rate))


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    from paddle_tpu.layers import ops as lops
    from paddle_tpu.layers import tensor as lt

    step = _decay_step_counter()
    div = lt.scale(step, scale=1.0 / float(decay_steps))
    if staircase:
        div = lops.floor(div)
    return lt.scale(lops.exp(lt.scale(div, scale=-decay_rate)), scale=float(learning_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    from paddle_tpu.layers import ops as lops
    from paddle_tpu.layers import tensor as lt

    step = _decay_step_counter()
    div = lt.scale(step, scale=1.0 / float(decay_steps))
    if staircase:
        div = lops.floor(div)
    denom = lt.scale(div, scale=float(decay_rate), bias=1.0)
    return lt.elementwise_div(lt.fill_constant([1], "float32", float(learning_rate)), denom)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001, power=1.0, cycle=False):
    from paddle_tpu.layers import ops as lops
    from paddle_tpu.layers import tensor as lt

    step = _decay_step_counter()
    capped = lt.elementwise_min(step, lt.fill_constant([1], "float32", float(decay_steps)))
    frac = lt.scale(capped, scale=1.0 / float(decay_steps))
    one_minus = lt.scale(frac, scale=-1.0, bias=1.0)
    poly = lt.elementwise_pow(one_minus, lt.fill_constant([1], "float32", float(power)))
    return lt.scale(poly, scale=float(learning_rate) - float(end_learning_rate), bias=float(end_learning_rate))


def piecewise_decay(boundaries, values):
    from paddle_tpu.layers import tensor as lt

    step = _decay_step_counter()
    lr = lt.fill_constant([1], "float32", float(values[-1]))
    # build nested where: smallest boundary first
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        cond = lt.less_than(step, lt.fill_constant([1], "float32", float(b)))
        lr = lt.where(cond, lt.fill_constant([1], "float32", float(v)), lr)
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    from paddle_tpu.layers import ops as lops
    from paddle_tpu.layers import tensor as lt

    step = _decay_step_counter()
    epoch = lops.floor(lt.scale(step, scale=1.0 / float(step_each_epoch)))
    cosv = lops.cos(lt.scale(epoch, scale=math.pi / float(epochs)))
    return lt.scale(lt.scale(cosv, scale=0.5, bias=0.5), scale=float(learning_rate))


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    from paddle_tpu.layers import tensor as lt

    step = _decay_step_counter()
    if isinstance(learning_rate, (int, float)):
        learning_rate = lt.fill_constant([1], "float32", float(learning_rate))
    frac = lt.scale(step, scale=1.0 / float(warmup_steps))
    warm = lt.scale(frac, scale=float(end_lr) - float(start_lr), bias=float(start_lr))
    cond = lt.less_than(step, lt.fill_constant([1], "float32", float(warmup_steps)))
    return lt.where(cond, warm, learning_rate)
