"""Input layers (reference: python/paddle/fluid/layers/io.py — data:41)."""
from __future__ import annotations

from paddle_tpu import framework
from paddle_tpu.core import types as core_types

__all__ = ["data"]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True, stop_gradient=True, **kwargs):
    """Declare an input variable.

    reference: layers/io.py:41.  ``append_batch_size`` prepends -1.
    ``lod_level>0`` declares a ragged sequence input; on TPU this is the
    padded+lengths encoding — a companion ``<name>_seq_len`` int32 var is
    created (see ops/sequence_ops.py docstring).
    """
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = framework.default_main_program().current_block()
    var = block.create_var(
        name=name,
        shape=shape,
        dtype=core_types.canonical_dtype(dtype),
        stop_gradient=stop_gradient,
        is_data=True,
        lod_level=lod_level,
    )
    if lod_level > 0:
        block.create_var(
            name=name + "_seq_len",
            shape=[-1],
            dtype="int32",
            stop_gradient=True,
            is_data=True,
        )
    if lod_level > 1:
        # nested (2-level) LoD: docs -> sentences -> words
        # (reference: lod_tensor.h:110 multi-level offsets).  Padded
        # encoding adds a per-outer-position inner length matrix
        # [B, S1max]; rows past a doc's sentence count are zero.
        block.create_var(
            name=name + "_inner_len",
            shape=[-1, -1],
            dtype="int32",
            stop_gradient=True,
            is_data=True,
        )
    if lod_level > 2:
        raise NotImplementedError(
            "padded LoD shim supports lod_level<=2 (docs->sents->words)"
        )
    return var
