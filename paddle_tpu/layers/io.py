"""Input layers (reference: python/paddle/fluid/layers/io.py — data:41)."""
from __future__ import annotations

from paddle_tpu import framework
from paddle_tpu.core import types as core_types

__all__ = ["data", "py_reader", "create_py_reader_by_data", "batch",
           "shuffle", "double_buffer", "load", "read_file", "open_files",
           "random_data_generator", "Preprocessor"]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True, stop_gradient=True, **kwargs):
    """Declare an input variable.

    reference: layers/io.py:41.  ``append_batch_size`` prepends -1.
    ``lod_level>0`` declares a ragged sequence input; on TPU this is the
    padded+lengths encoding — a companion ``<name>_seq_len`` int32 var is
    created (see ops/sequence_ops.py docstring).
    """
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = framework.default_main_program().current_block()
    var = block.create_var(
        name=name,
        shape=shape,
        dtype=core_types.canonical_dtype(dtype),
        stop_gradient=stop_gradient,
        is_data=True,
        lod_level=lod_level,
    )
    if lod_level > 0:
        block.create_var(
            name=name + "_seq_len",
            shape=[-1],
            dtype="int32",
            stop_gradient=True,
            is_data=True,
        )
    # nested (N-level) LoD (reference: lod_tensor.h:110,:229 — recursively
    # nested offsets).  Padded encoding: level k's companion length tensor
    # has one entry per unit at level k-1, so its shape is [B, S1..Sk]
    # (entries past a unit's child count are zero).  Level 1 keeps the
    # historical ``_inner_len`` name; deeper levels are ``_inner_len_k``.
    for level in range(1, lod_level):
        suffix = "_inner_len" if level == 1 else "_inner_len_%d" % level
        block.create_var(
            name=name + suffix,
            shape=[-1] * (level + 1),
            dtype="int32",
            stop_gradient=True,
            is_data=True,
        )
    return var


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """reference: layers/io.py py_reader — returns a PyReader-like
    object; feed it with decorate_paddle_reader/decorate_batch_generator
    and iterate (the TPU build feeds the compiled step directly, see
    paddle_tpu/reader.py PyReader)."""
    from paddle_tpu import reader as reader_mod

    feed_names = [name or "pyr_%d" % i for i, _ in enumerate(shapes)]
    return reader_mod.PyReader(
        feed_list=None, capacity=capacity, use_double_buffer=use_double_buffer,
        iterable=True,
    )


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    """reference: layers/io.py create_py_reader_by_data."""
    from paddle_tpu import reader as reader_mod

    return reader_mod.PyReader(
        feed_list=feed_list, capacity=capacity,
        use_double_buffer=use_double_buffer, iterable=True,
    )


def batch(reader, batch_size, drop_last=False):
    """reference: layers/io.py batch (decorator form)."""
    from paddle_tpu import reader as reader_mod

    return reader_mod.batch(reader, batch_size, drop_last)


def shuffle(reader, buffer_size):
    """reference: layers/io.py shuffle (decorator form)."""
    from paddle_tpu import reader as reader_mod

    return reader_mod.shuffle(reader, buffer_size)


def double_buffer(reader, place=None, name=None):
    """reference: layers/io.py double_buffer — the TPU reader pipeline
    double-buffers device puts internally (reader.py), so this is the
    identity on an already-wrapped reader."""
    return reader


def load(out, file_path, load_as_fp16=None):
    """reference: layers/io.py load — load one persistable var's value
    from an io.save_vars file into the scope var at startup."""
    from paddle_tpu import io as io_mod
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("load")
    helper.append_op(
        type="load", inputs={}, outputs={"Out": [out]},
        attrs={"file_path": file_path},
    )
    return out


def read_file(reader):
    """reference: layers/io.py read_file — the file-reader op family is
    replaced by host readers feeding the compiled step; use
    paddle_tpu.reader / fluid_dataset instead."""
    raise NotImplementedError(
        "read_file: use paddle_tpu.reader readers or DatasetFactory "
        "(the TPU input path is host-side, reader.py)"
    )


def open_files(filenames, shapes, lod_levels, dtypes, thread_num=None,
               buffer_size=None, pass_num=1, is_test=None):
    """reference: layers/io.py open_files (see read_file)."""
    raise NotImplementedError(
        "open_files: use DatasetFactory (fluid_dataset.py) or "
        "paddle_tpu.reader file readers"
    )


def random_data_generator(low, high, shapes, lod_levels, for_parallel=True):
    """reference: layers/io.py random_data_generator (see read_file)."""
    raise NotImplementedError(
        "random_data_generator: feed numpy batches or use "
        "layers.uniform_random_batch_size_like inside the program"
    )


class Preprocessor:
    """reference: layers/io.py Preprocessor — graph-side reader
    preprocessing; host readers own preprocessing on this build."""

    def __init__(self, reader, name=None):
        raise NotImplementedError(
            "Preprocessor: preprocess in the host reader (reader.py) — "
            "XLA fuses any in-program math anyway"
        )
