"""Robustness metrics (process-global registry, always on).

The counters every fault-tolerance mechanism reports through: armed
fault points count their injections here, ``RetryPolicy`` counts every
granted retry, the half-open circuit breakers (wire fleet backends AND
in-process serving replicas — the shared ``pool`` label distinguishes
them) count their probe admissions, and the training checkpointer
counts completed atomic saves.  ``tools/check_metrics_docs.py`` holds
the README table to this set like every other metrics module.
"""
from __future__ import annotations

from paddle_tpu.monitor import registry as _registry

__all__ = [
    "FAULTS_INJECTED", "RETRY_ATTEMPTS",
    "BACKEND_HALFOPEN_PROBES", "TRAIN_CHECKPOINTS",
    "TRAIN_CHECKPOINT_RESTORES", "TRAIN_CHECKPOINT_FALLBACKS",
    "TRAIN_CHECKPOINT_CORRUPTION", "TRAIN_CHECKPOINT_BYTES",
]

FAULTS_INJECTED = _registry.REGISTRY.counter(
    "faults_injected_total",
    "fault-point triggers that actually fired an armed injection "
    "(point=<faultpoint name>)", ("point",))
RETRY_ATTEMPTS = _registry.REGISTRY.counter(
    "retry_attempts_total",
    "retries granted by a RetryPolicy budget, after the backoff sleep "
    "(op=<call site>)", ("op",))
BACKEND_HALFOPEN_PROBES = _registry.REGISTRY.counter(
    "backend_halfopen_probes_total",
    "half-open circuit-breaker probes: a retired backend/replica "
    "admitted one trial after its cooldown (pool=<fleet or server>)",
    ("pool",))
TRAIN_CHECKPOINTS = _registry.REGISTRY.counter(
    "train_checkpoints_total",
    "training checkpoints committed (atomic tmp+rename completed)")
TRAIN_CHECKPOINT_RESTORES = _registry.REGISTRY.counter(
    "train_checkpoint_restore_total",
    "checkpoints successfully restored (integrity-verified; cross-mesh "
    "shard-exchange restores count here too)")
TRAIN_CHECKPOINT_FALLBACKS = _registry.REGISTRY.counter(
    "train_checkpoint_fallback_total",
    "restore fell back past a checkpoint it could not use (corrupt, "
    "truncated, or a dangling LATEST pointer) to an older complete one "
    "— counted per checkpoint skipped, never silent")
TRAIN_CHECKPOINT_CORRUPTION = _registry.REGISTRY.counter(
    "train_checkpoint_corruption_total",
    "checkpoints that failed integrity verification at restore "
    "(content-hash mismatch, truncated or missing files)")
TRAIN_CHECKPOINT_BYTES = _registry.REGISTRY.gauge(
    "train_checkpoint_bytes",
    "total on-disk bytes of the last committed training checkpoint "
    "(every file the integrity manifest covers)")
