"""Crash-resumable training checkpoints.

The reference recovered a dead trainer by reloading persistables and a
PS table checkpoint (checkpoint_notify) and re-reading the dataset from
the top; this manager makes the recovery *exact to a step*: one
checkpoint atomically captures

1. the program's persistables (params + optimizer accumulators + LR),
   via ``io.save_persistables`` into the checkpoint directory,
2. the PS sparse tables (``PSClient.save`` row dump, restored by value
   with the ``assign`` op — not replayed through the optimizer), and
3. the dataset **cursor** (completed-step count + caller epoch), so a
   resumed ``train_from_dataset`` skips exactly the batches already
   consumed instead of restarting the epoch.

Atomicity is tmp+rename at every level: a checkpoint is staged under
``<run_dir>/.tmp-<step>``, ``os.replace``d to ``ckpt-<step>`` only when
complete, and only then does the ``LATEST`` pointer move (itself via
tmp+rename).  A SIGKILL at ANY instant leaves either the previous
checkpoint or the new one — never a half-written directory a resume
could trust.

**Async mode** (:meth:`save_async`) takes the save cost off the
training critical path: the caller (already quiesced) pays only a
copy-on-write gather — persistables copied to host numpy, PS tables
dumped by value — and serialization + commit happen on a background
snapshot thread.  The atomicity story is unchanged (the background
writer goes through the same tmp+rename commit), so a SIGKILL DURING a
background save leaves the previous committed checkpoint in charge;
the ``checkpoint.commit`` fault point injects delay/error into the
commit phase so chaos tests can pin exactly that window.  One save is
in flight at a time: a new ``save_async`` (or :meth:`wait`) joins the
previous one first.

**Shard-wise mode** (``save(..., compiled=<CompiledProgram>)``): a
mesh-sharded training run (``paddle_tpu.sharding.train``) must not
funnel every parameter and optimizer moment through one host buffer —
at scale the full tensor does not FIT one host.  With ``compiled=``
given, each mesh-committed persistable is saved as its **addressable
shards**: one ``.npy`` per distinct shard (replicas deduplicated) plus
a shard manifest recording the global shape, dtype, PartitionSpec, and
each shard's index slices.  No full tensor is ever materialized — the
per-shard files ARE the checkpoint (their shapes prove it).  Restore
(``restore(..., compiled=)``) re-places each shard straight onto its
device via ``jax.make_array_from_single_device_arrays``, again without
a full host tensor; resuming on a mesh with a DIFFERENT shape (or a
layout whose shard indexes no longer match) is a typed
:class:`CheckpointMeshMismatchError`, never silent mis-placement.
Shard-wise saves compose with async mode and the atomic-commit /
``checkpoint.commit`` fault-point machinery unchanged.

Layout::

    run_dir/
      LATEST              # "ckpt-000040\n"
      ckpt-000040/
        cursor.json       # {"step": 40, "epoch": 0}
        params/           # io.save_persistables output (host-resident
                          #   vars only in shard-wise mode)
        shards/           # optional: manifest.json + v<i>_s<j>.npy —
                          #   per-shard dumps of mesh-committed state
        ps/               # optional: manifest.json + t<i>_{ids,rows}.npy
                          #   (+ t<i>_moments.npy: adagrad accumulators)
"""
from __future__ import annotations

import json
import os
import shutil
import threading
from typing import Dict, Optional, Tuple

import numpy as np

import paddle_tpu.faults as _faults
from paddle_tpu.faults.metrics import TRAIN_CHECKPOINTS

__all__ = ["TrainCheckpoint", "CheckpointMeshMismatchError"]


class CheckpointMeshMismatchError(RuntimeError):
    """A shard-wise checkpoint cannot re-place on the CURRENT mesh or
    layout: the mesh shape differs from the one the shards were saved
    under, or a device's expected shard index has no saved file.
    Resuming anyway would silently mis-place state; re-shard offline or
    resume on the original mesh shape."""


def _index_key(index, shape) -> Tuple[Tuple[int, int], ...]:
    """Normalize a shard index (tuple of slices over the global shape)
    to a hashable/JSON-safe ((start, stop), ...) key."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)

_LATEST = "LATEST"
_PREFIX = "ckpt-"
_TMP_PREFIX = ".tmp-"


class TrainCheckpoint:
    """One run directory's checkpoint manager.

    ``every_n_steps``: cadence for :meth:`should_save` (0 disables the
    periodic trigger; explicit :meth:`save` always works).
    ``keep``: finished checkpoints retained (older ones are pruned
    after each successful commit; the latest is never pruned).
    """

    def __init__(self, run_dir: str, every_n_steps: int = 0, keep: int = 2):
        self.run_dir = str(run_dir)
        self.every_n_steps = int(every_n_steps)
        self.keep = max(1, int(keep))
        self._bg: Optional[threading.Thread] = None
        self._bg_result: Optional[str] = None
        self._bg_error: Optional[BaseException] = None
        os.makedirs(self.run_dir, exist_ok=True)

    # ------------------------------------------------------------------
    def should_save(self, completed_steps: int) -> bool:
        return (self.every_n_steps > 0
                and completed_steps > 0
                and completed_steps % self.every_n_steps == 0)

    def _name(self, step: int) -> str:
        return "%s%06d" % (_PREFIX, int(step))

    # ------------------------------------------------------------------
    def save(self, program, scope, step: int, epoch: int = 0,
             ps_client=None, extra: Optional[Dict] = None,
             compiled=None) -> str:
        """Commit one checkpoint; returns the finished directory path.
        ``step`` is the number of COMPLETED steps (the resume cursor).
        ``compiled``: the CompiledProgram a sharded training run
        executes through — mesh-committed state then saves SHARD-wise
        (each device's addressable shards, never a gathered full
        tensor).  The caller is responsible for quiescing async state
        first (the executor joins its overlapped PS pull and flushes
        the Communicator before calling)."""
        self.wait()  # never interleave with an in-flight async save
        ps_state = (self._gather_ps(ps_client)
                    if ps_client is not None else None)
        shard_state = self._gather_shards(program, scope, compiled,
                                          copy=False)
        return self._commit(program, scope, step, epoch, ps_state, extra,
                            shard_state)

    def save_async(self, program, scope, step: int, epoch: int = 0,
                   ps_client=None, extra: Optional[Dict] = None,
                   compiled=None) -> None:
        """Snapshot now, serialize in the background.

        The caller-thread cost is one copy-on-write gather: every
        persistable's value copied to host numpy (into a detached
        snapshot scope; mesh-committed state copies PER SHARD — the
        full tensor is never materialized) and the PS tables dumped by
        value — the PS sockets are only touched here, never from the
        writer thread.  Serialization, fsync traffic, the tmp+rename
        commit, and pruning all happen on a daemon snapshot thread;
        training continues immediately.  A previous in-flight save is
        joined first (its error, if any, re-raises HERE — a silent
        checkpoint gap must not go unnoticed); call :meth:`wait` at end
        of epoch to commit the tail save."""
        self.wait()
        shard_state = self._gather_shards(program, scope, compiled,
                                          copy=True)
        exclude = set(shard_state["vars"]) if shard_state else ()
        snap = self._snapshot_scope(program, scope, exclude=exclude)
        ps_state = (self._gather_ps(ps_client)
                    if ps_client is not None else None)
        self._bg_result = self._bg_error = None

        def _write():
            try:
                self._bg_result = self._commit(
                    program, snap, step, epoch, ps_state, extra,
                    shard_state)
            except BaseException as e:  # noqa: BLE001 — re-raised at wait()
                self._bg_error = e

        self._bg = threading.Thread(
            target=_write, name="ckpt-writer-%06d" % int(step), daemon=True)
        self._bg.start()

    def wait(self, timeout: Optional[float] = None) -> Optional[str]:
        """Join the in-flight background save, if any.  Returns its
        committed path (None when nothing was in flight) and re-raises
        its failure."""
        bg, self._bg = self._bg, None
        if bg is not None:
            bg.join(timeout)
            if bg.is_alive():  # caller keeps ownership of the join
                self._bg = bg
                raise TimeoutError("background checkpoint still writing")
        if self._bg_error is not None:
            err, self._bg_error = self._bg_error, None
            raise err
        result, self._bg_result = self._bg_result, None
        return result

    @property
    def in_flight(self) -> bool:
        return self._bg is not None and self._bg.is_alive()

    @staticmethod
    def _snapshot_scope(program, scope, exclude=()):
        """Copy every persistable's current value into a detached
        scope: the writer thread reads ONLY these copies, so training
        may mutate the live scope the instant this returns.
        ``exclude``: names captured elsewhere (the shard-wise gather) —
        copying them here would materialize the full tensor."""
        from paddle_tpu import io as _io
        from paddle_tpu.scope import Scope

        snap = Scope()
        for v in _io._collect(program, _io._is_persistable, None):
            if v.name in exclude:
                continue
            val = scope.get(v.name)
            if val is not None:
                snap.set(v.name, np.array(np.asarray(val), copy=True))
        return snap

    @staticmethod
    def _gather_shards(program, scope, compiled, copy: bool):
        """Collect mesh-committed persistables as per-shard host arrays
        (replicas deduplicated by shard index).  Returns None when
        ``compiled`` is None or nothing is mesh-committed.  Each shard
        copies only ITS slice to host — the full tensor never exists in
        one buffer.  ``copy=True`` (async mode) forces an owned numpy
        copy so a donated device buffer mutated by the next step cannot
        reach the writer thread."""
        if compiled is None:
            return None
        from paddle_tpu import io as _io
        from paddle_tpu.sharding.rules import spec_to_manifest

        mesh = compiled.mesh
        mesh_axes = {str(a): int(n) for a, n in
                     zip(mesh.axis_names, mesh.devices.shape)}
        entries: Dict[str, Dict] = {}
        for v in _io._collect(program, _io._is_persistable, None):
            val = scope.get(v.name)
            shards = getattr(val, "addressable_shards", None)
            sh = getattr(val, "sharding", None)
            if (not shards or sh is None
                    or len(getattr(sh, "device_set", ())) <= 1):
                continue  # host / single-device value: params/ path
            if getattr(sh, "is_fully_replicated", False):
                # every device holds the FULL value (plain data-parallel
                # state, norms/LR under a sharded layout): the params/
                # path saves one portable host copy — routing it through
                # shards/ would pin a replicated checkpoint to this
                # mesh's exact shape for zero space win
                continue
            shape = tuple(int(d) for d in val.shape)
            seen: Dict[Tuple, np.ndarray] = {}
            for s in shards:
                key = _index_key(s.index, shape)
                if key in seen:
                    continue  # a replica of an already-captured shard
                arr = np.asarray(s.data)  # THIS shard only, never full
                if copy:
                    arr = np.array(arr, copy=True)
                seen[key] = arr
            spec = getattr(sh, "spec", None)
            entries[v.name] = {
                "shape": shape,
                "dtype": str(val.dtype),
                "spec": (spec_to_manifest(spec)
                         if spec is not None else None),
                "shards": sorted(seen.items()),
            }
        if not entries:
            return None
        return {"mesh_axes": mesh_axes, "vars": entries}

    def _commit(self, program, scope, step, epoch, ps_state, extra,
                shard_state=None) -> str:
        """The write + atomic-rename phase (caller thread for ``save``,
        snapshot thread for ``save_async``); reads only the given scope
        and the pre-gathered ``ps_state``/``shard_state``."""
        from paddle_tpu import io as _io

        final = os.path.join(self.run_dir, self._name(step))
        tmp = os.path.join(self.run_dir, _TMP_PREFIX + self._name(step))
        for stale in (tmp, final):  # a crashed previous attempt
            if os.path.isdir(stale):
                shutil.rmtree(stale)
        os.makedirs(tmp)
        shard_names = set(shard_state["vars"]) if shard_state else set()
        _io.save_vars(
            None, os.path.join(tmp, "params"), main_program=program,
            predicate=lambda v: (_io._is_persistable(v)
                                 and v.name not in shard_names),
            scope=scope)
        if shard_state is not None:
            self._write_shards(os.path.join(tmp, "shards"), shard_state)
        if ps_state is not None:
            self._write_ps(os.path.join(tmp, "ps"), ps_state)
        cursor = {"step": int(step), "epoch": int(epoch)}
        if extra:
            cursor.update(extra)
        with open(os.path.join(tmp, "cursor.json"), "w") as f:
            json.dump(cursor, f)
        if _faults.active is not None:  # disarmed: one is-None gate
            # the chaos window: a kill/delay/error HERE lands between a
            # fully staged tmp dir and its commit — resume must still
            # see only the previous committed checkpoint
            _faults.active.faultpoint(
                "checkpoint.commit", run_dir=self.run_dir, step=int(step))
        os.replace(tmp, final)
        # move LATEST only after the checkpoint directory is committed
        ptr_tmp = os.path.join(self.run_dir, _LATEST + ".tmp")
        with open(ptr_tmp, "w") as f:
            f.write(self._name(step) + "\n")
        os.replace(ptr_tmp, os.path.join(self.run_dir, _LATEST))
        TRAIN_CHECKPOINTS.inc()
        self._prune(keep_name=self._name(step))
        return final

    @staticmethod
    def _write_shards(dirname: str, shard_state) -> None:
        """One ``.npy`` per distinct shard plus a manifest tying each
        file to its variable, global shape/dtype/spec, and index
        slices.  File shapes ARE shard shapes — the on-disk proof that
        no full tensor was gathered."""
        os.makedirs(dirname)
        manifest = {"mesh_axes": shard_state["mesh_axes"], "vars": {}}
        for i, (name, ent) in enumerate(sorted(
                shard_state["vars"].items())):
            files = []
            for j, (key, arr) in enumerate(ent["shards"]):
                fname = "v%03d_s%02d.npy" % (i, j)
                np.save(os.path.join(dirname, fname), arr)
                files.append({"file": fname,
                              "index": [list(se) for se in key]})
            manifest["vars"][name] = {
                "shape": list(ent["shape"]),
                "dtype": ent["dtype"],
                "spec": ent["spec"],
                "shards": files,
            }
        with open(os.path.join(dirname, "manifest.json"), "w") as f:
            json.dump(manifest, f)

    @staticmethod
    def _gather_ps(ps_client):
        # include_moments: the adagrad accumulators dump alongside the
        # rows so a SIGKILL-resume is exact for sparse optimizers (a
        # moment-less restore would restart per-row step sizes at their
        # largest and re-diverge the loss trajectory)
        return ps_client.save(include_moments=True)

    @staticmethod
    def _write_ps(dirname: str, state) -> None:
        os.makedirs(dirname)
        manifest = []
        for i, (table, value) in enumerate(sorted(state.items())):
            ids, rows = value[0], value[1]
            moments = value[2] if len(value) == 3 else None
            np.save(os.path.join(dirname, "t%03d_ids.npy" % i),
                    np.asarray(ids, np.int64))
            np.save(os.path.join(dirname, "t%03d_rows.npy" % i),
                    np.asarray(rows, np.float32))
            if moments is not None:
                np.save(os.path.join(dirname, "t%03d_moments.npy" % i),
                        np.asarray(moments, np.float32))
            manifest.append({"table": table, "index": i,
                             "dim": int(rows.shape[1]) if rows.size else 0,
                             "moments": moments is not None})
        with open(os.path.join(dirname, "manifest.json"), "w") as f:
            json.dump({"tables": manifest}, f)

    @staticmethod
    def _step_of(name: str) -> int:
        try:
            return int(name[len(_PREFIX):])
        except ValueError:
            return -1

    def _prune(self, keep_name: str) -> None:
        # numeric order, not lexicographic: a step past the %06d padding
        # must never make a NEWER checkpoint sort as the oldest
        done = sorted(
            (d for d in os.listdir(self.run_dir)
             if d.startswith(_PREFIX)
             and os.path.isdir(os.path.join(self.run_dir, d))),
            key=self._step_of)
        excess = [d for d in done[:-self.keep] if d != keep_name]
        for d in excess:
            shutil.rmtree(os.path.join(self.run_dir, d), ignore_errors=True)

    # ------------------------------------------------------------------
    def latest(self) -> Optional[str]:
        """Path of the newest COMMITTED checkpoint, or None."""
        ptr = os.path.join(self.run_dir, _LATEST)
        if not os.path.exists(ptr):
            return None
        with open(ptr) as f:
            name = f.read().strip()
        path = os.path.join(self.run_dir, name)
        return path if os.path.isdir(path) else None

    def restore(self, program, scope, ps_client=None,
                compiled=None) -> Optional[Dict]:
        """Restore the newest checkpoint into ``scope`` (and the PS
        tables through ``ps_client``); returns its cursor dict, or None
        when the run directory holds no committed checkpoint (fresh
        start).  A shard-wise checkpoint needs ``compiled`` (the same
        sharded layout the run trains through) so each shard re-places
        straight onto its device — a mesh whose shape differs from the
        saved one is a typed :class:`CheckpointMeshMismatchError`."""
        from paddle_tpu import io as _io

        path = self.latest()
        if path is None:
            return None
        _io.load_persistables(None, os.path.join(path, "params"),
                              main_program=program, scope=scope)
        shards_dir = os.path.join(path, "shards")
        if os.path.isdir(shards_dir):
            if compiled is None:
                raise ValueError(
                    "checkpoint %s holds SHARD-wise state — pass the "
                    "run's CompiledProgram (compiled=) so shards "
                    "re-place onto its mesh" % path)
            self._restore_shards(shards_dir, scope, compiled)
        ps_dir = os.path.join(path, "ps")
        if os.path.isdir(ps_dir):
            if ps_client is None:
                raise ValueError(
                    "checkpoint %s carries PS tables but no ps_client was "
                    "given to restore them" % path)
            self._restore_ps(ps_dir, ps_client)
            cache = getattr(program, "_embedding_cache", None)
            if cache is not None:
                # the restore rewrote rows wholesale server-side: a
                # cached copy from before it is stale (regression-pinned
                # in tests/test_embedding_cache.py)
                cache.invalidate()
        with open(os.path.join(path, "cursor.json")) as f:
            return json.load(f)

    @staticmethod
    def _restore_shards(dirname: str, scope, compiled) -> None:
        """Re-place saved shards onto the compiled program's mesh: each
        device receives exactly its index's shard via ``device_put`` +
        ``make_array_from_single_device_arrays`` — the full tensor is
        never assembled host-side.  Typed failures: a mesh shape
        differing from the saved one, a layout whose resolved spec
        drifted from the saved spec, or a device index with no saved
        shard file."""
        import jax

        from paddle_tpu.sharding.rules import spec_to_manifest

        with open(os.path.join(dirname, "manifest.json")) as f:
            manifest = json.load(f)
        mesh = compiled.mesh
        cur_axes = {str(a): int(n) for a, n in
                    zip(mesh.axis_names, mesh.devices.shape)}
        saved_axes = {str(a): int(n)
                      for a, n in manifest["mesh_axes"].items()}
        if cur_axes != saved_axes:
            raise CheckpointMeshMismatchError(
                "shard-wise checkpoint was saved on mesh %s but this "
                "run's mesh is %s — shards cannot re-place on a "
                "different mesh shape (resume on the original shape, "
                "or re-shard offline)" % (saved_axes, cur_axes))

        def _norm(doc):
            doc = list(doc or [])
            while doc and doc[-1] is None:
                doc.pop()  # trailing replicated dims are spec-equal
            return doc

        for name, ent in manifest["vars"].items():
            sharding = compiled.state_sharding(name)
            shape = tuple(int(d) for d in ent["shape"])
            saved_spec = ent.get("spec")
            cur_spec = spec_to_manifest(sharding.spec)
            if saved_spec is not None and _norm(saved_spec) != _norm(
                    cur_spec):
                raise CheckpointMeshMismatchError(
                    "var %r was saved with partition spec %s but the "
                    "current layout resolves it to %s — the rules "
                    "changed since the checkpoint; restore with the "
                    "saving layout" % (name, saved_spec, cur_spec))
            by_index = {}
            for doc in ent["shards"]:
                key = tuple(tuple(int(x) for x in se)
                            for se in doc["index"])
                by_index[key] = os.path.join(dirname, doc["file"])
            loaded: Dict[Tuple, np.ndarray] = {}
            arrays = []
            for dev, idx in sharding.addressable_devices_indices_map(
                    shape).items():
                key = _index_key(idx, shape)
                fpath = by_index.get(key)
                if fpath is None:
                    raise CheckpointMeshMismatchError(
                        "var %r: device %s expects shard index %s but "
                        "the checkpoint holds only %s — layout/mesh "
                        "drift since the save"
                        % (name, dev, key, sorted(by_index)))
                arr = loaded.get(key)
                if arr is None:
                    arr = loaded[key] = np.load(fpath)
                arrays.append(jax.device_put(arr, dev))
            scope.set(name, jax.make_array_from_single_device_arrays(
                shape, sharding, arrays))

    @staticmethod
    def _restore_ps(dirname: str, ps_client) -> None:
        with open(os.path.join(dirname, "manifest.json")) as f:
            manifest = json.load(f)
        state = {}
        for ent in manifest["tables"]:
            i = int(ent["index"])
            ids = np.load(os.path.join(dirname, "t%03d_ids.npy" % i))
            rows = np.load(os.path.join(dirname, "t%03d_rows.npy" % i))
            mpath = os.path.join(dirname, "t%03d_moments.npy" % i)
            # pre-moments checkpoints (no flag, no file) restore as
            # before: rows only, accumulators restart
            if ent.get("moments") and os.path.exists(mpath):
                state[str(ent["table"])] = (ids, rows, np.load(mpath))
            else:
                state[str(ent["table"])] = (ids, rows)
        ps_client.load_tables(state)
