"""Crash-resumable training checkpoints.

The reference recovered a dead trainer by reloading persistables and a
PS table checkpoint (checkpoint_notify) and re-reading the dataset from
the top; this manager makes the recovery *exact to a step*: one
checkpoint atomically captures

1. the program's persistables (params + optimizer accumulators + LR),
   via ``io.save_persistables`` into the checkpoint directory,
2. the PS sparse tables (``PSClient.save`` row dump, restored by value
   with the ``assign`` op — not replayed through the optimizer), and
3. the dataset **cursor** (completed-step count + caller epoch), so a
   resumed ``train_from_dataset`` skips exactly the batches already
   consumed instead of restarting the epoch.

Atomicity is tmp+rename at every level: a checkpoint is staged under
``<run_dir>/.tmp-<step>``, ``os.replace``d to ``ckpt-<step>`` only when
complete, and only then does the ``LATEST`` pointer move (itself via
tmp+rename).  A SIGKILL at ANY instant leaves either the previous
checkpoint or the new one — never a half-written directory a resume
could trust.

**Async mode** (:meth:`save_async`) takes the save cost off the
training critical path: the caller (already quiesced) pays only a
copy-on-write gather — persistables copied to host numpy, PS tables
dumped by value — and serialization + commit happen on a background
snapshot thread.  The atomicity story is unchanged (the background
writer goes through the same tmp+rename commit), so a SIGKILL DURING a
background save leaves the previous committed checkpoint in charge;
the ``checkpoint.commit`` fault point injects delay/error into the
commit phase so chaos tests can pin exactly that window.  One save is
in flight at a time: a new ``save_async`` (or :meth:`wait`) joins the
previous one first.

Layout::

    run_dir/
      LATEST              # "ckpt-000040\n"
      ckpt-000040/
        cursor.json       # {"step": 40, "epoch": 0}
        params/           # io.save_persistables output
        ps/               # optional: manifest.json + t<i>_{ids,rows}.npy
                          #   (+ t<i>_moments.npy: adagrad accumulators)
"""
from __future__ import annotations

import json
import os
import shutil
import threading
from typing import Dict, Optional

import numpy as np

import paddle_tpu.faults as _faults
from paddle_tpu.faults.metrics import TRAIN_CHECKPOINTS

__all__ = ["TrainCheckpoint"]

_LATEST = "LATEST"
_PREFIX = "ckpt-"
_TMP_PREFIX = ".tmp-"


class TrainCheckpoint:
    """One run directory's checkpoint manager.

    ``every_n_steps``: cadence for :meth:`should_save` (0 disables the
    periodic trigger; explicit :meth:`save` always works).
    ``keep``: finished checkpoints retained (older ones are pruned
    after each successful commit; the latest is never pruned).
    """

    def __init__(self, run_dir: str, every_n_steps: int = 0, keep: int = 2):
        self.run_dir = str(run_dir)
        self.every_n_steps = int(every_n_steps)
        self.keep = max(1, int(keep))
        self._bg: Optional[threading.Thread] = None
        self._bg_result: Optional[str] = None
        self._bg_error: Optional[BaseException] = None
        os.makedirs(self.run_dir, exist_ok=True)

    # ------------------------------------------------------------------
    def should_save(self, completed_steps: int) -> bool:
        return (self.every_n_steps > 0
                and completed_steps > 0
                and completed_steps % self.every_n_steps == 0)

    def _name(self, step: int) -> str:
        return "%s%06d" % (_PREFIX, int(step))

    # ------------------------------------------------------------------
    def save(self, program, scope, step: int, epoch: int = 0,
             ps_client=None, extra: Optional[Dict] = None) -> str:
        """Commit one checkpoint; returns the finished directory path.
        ``step`` is the number of COMPLETED steps (the resume cursor).
        The caller is responsible for quiescing async state first (the
        executor joins its overlapped PS pull and flushes the
        Communicator before calling)."""
        self.wait()  # never interleave with an in-flight async save
        ps_state = (self._gather_ps(ps_client)
                    if ps_client is not None else None)
        return self._commit(program, scope, step, epoch, ps_state, extra)

    def save_async(self, program, scope, step: int, epoch: int = 0,
                   ps_client=None, extra: Optional[Dict] = None) -> None:
        """Snapshot now, serialize in the background.

        The caller-thread cost is one copy-on-write gather: every
        persistable's value copied to host numpy (into a detached
        snapshot scope) and the PS tables dumped by value — the PS
        sockets are only touched here, never from the writer thread.
        Serialization, fsync traffic, the tmp+rename commit, and
        pruning all happen on a daemon snapshot thread; training
        continues immediately.  A previous in-flight save is joined
        first (its error, if any, re-raises HERE — a silent checkpoint
        gap must not go unnoticed); call :meth:`wait` at end of epoch
        to commit the tail save."""
        self.wait()
        snap = self._snapshot_scope(program, scope)
        ps_state = (self._gather_ps(ps_client)
                    if ps_client is not None else None)
        self._bg_result = self._bg_error = None

        def _write():
            try:
                self._bg_result = self._commit(
                    program, snap, step, epoch, ps_state, extra)
            except BaseException as e:  # noqa: BLE001 — re-raised at wait()
                self._bg_error = e

        self._bg = threading.Thread(
            target=_write, name="ckpt-writer-%06d" % int(step), daemon=True)
        self._bg.start()

    def wait(self, timeout: Optional[float] = None) -> Optional[str]:
        """Join the in-flight background save, if any.  Returns its
        committed path (None when nothing was in flight) and re-raises
        its failure."""
        bg, self._bg = self._bg, None
        if bg is not None:
            bg.join(timeout)
            if bg.is_alive():  # caller keeps ownership of the join
                self._bg = bg
                raise TimeoutError("background checkpoint still writing")
        if self._bg_error is not None:
            err, self._bg_error = self._bg_error, None
            raise err
        result, self._bg_result = self._bg_result, None
        return result

    @property
    def in_flight(self) -> bool:
        return self._bg is not None and self._bg.is_alive()

    @staticmethod
    def _snapshot_scope(program, scope):
        """Copy every persistable's current value into a detached
        scope: the writer thread reads ONLY these copies, so training
        may mutate the live scope the instant this returns."""
        from paddle_tpu import io as _io
        from paddle_tpu.scope import Scope

        snap = Scope()
        for v in _io._collect(program, _io._is_persistable, None):
            val = scope.get(v.name)
            if val is not None:
                snap.set(v.name, np.array(np.asarray(val), copy=True))
        return snap

    def _commit(self, program, scope, step, epoch, ps_state, extra) -> str:
        """The write + atomic-rename phase (caller thread for ``save``,
        snapshot thread for ``save_async``); reads only the given scope
        and the pre-gathered ``ps_state``."""
        from paddle_tpu import io as _io

        final = os.path.join(self.run_dir, self._name(step))
        tmp = os.path.join(self.run_dir, _TMP_PREFIX + self._name(step))
        for stale in (tmp, final):  # a crashed previous attempt
            if os.path.isdir(stale):
                shutil.rmtree(stale)
        os.makedirs(tmp)
        _io.save_persistables(None, os.path.join(tmp, "params"),
                              main_program=program, scope=scope)
        if ps_state is not None:
            self._write_ps(os.path.join(tmp, "ps"), ps_state)
        cursor = {"step": int(step), "epoch": int(epoch)}
        if extra:
            cursor.update(extra)
        with open(os.path.join(tmp, "cursor.json"), "w") as f:
            json.dump(cursor, f)
        if _faults.active is not None:  # disarmed: one is-None gate
            # the chaos window: a kill/delay/error HERE lands between a
            # fully staged tmp dir and its commit — resume must still
            # see only the previous committed checkpoint
            _faults.active.faultpoint(
                "checkpoint.commit", run_dir=self.run_dir, step=int(step))
        os.replace(tmp, final)
        # move LATEST only after the checkpoint directory is committed
        ptr_tmp = os.path.join(self.run_dir, _LATEST + ".tmp")
        with open(ptr_tmp, "w") as f:
            f.write(self._name(step) + "\n")
        os.replace(ptr_tmp, os.path.join(self.run_dir, _LATEST))
        TRAIN_CHECKPOINTS.inc()
        self._prune(keep_name=self._name(step))
        return final

    @staticmethod
    def _gather_ps(ps_client):
        # include_moments: the adagrad accumulators dump alongside the
        # rows so a SIGKILL-resume is exact for sparse optimizers (a
        # moment-less restore would restart per-row step sizes at their
        # largest and re-diverge the loss trajectory)
        return ps_client.save(include_moments=True)

    @staticmethod
    def _write_ps(dirname: str, state) -> None:
        os.makedirs(dirname)
        manifest = []
        for i, (table, value) in enumerate(sorted(state.items())):
            ids, rows = value[0], value[1]
            moments = value[2] if len(value) == 3 else None
            np.save(os.path.join(dirname, "t%03d_ids.npy" % i),
                    np.asarray(ids, np.int64))
            np.save(os.path.join(dirname, "t%03d_rows.npy" % i),
                    np.asarray(rows, np.float32))
            if moments is not None:
                np.save(os.path.join(dirname, "t%03d_moments.npy" % i),
                        np.asarray(moments, np.float32))
            manifest.append({"table": table, "index": i,
                             "dim": int(rows.shape[1]) if rows.size else 0,
                             "moments": moments is not None})
        with open(os.path.join(dirname, "manifest.json"), "w") as f:
            json.dump({"tables": manifest}, f)

    @staticmethod
    def _step_of(name: str) -> int:
        try:
            return int(name[len(_PREFIX):])
        except ValueError:
            return -1

    def _prune(self, keep_name: str) -> None:
        # numeric order, not lexicographic: a step past the %06d padding
        # must never make a NEWER checkpoint sort as the oldest
        done = sorted(
            (d for d in os.listdir(self.run_dir)
             if d.startswith(_PREFIX)
             and os.path.isdir(os.path.join(self.run_dir, d))),
            key=self._step_of)
        excess = [d for d in done[:-self.keep] if d != keep_name]
        for d in excess:
            shutil.rmtree(os.path.join(self.run_dir, d), ignore_errors=True)

    # ------------------------------------------------------------------
    def latest(self) -> Optional[str]:
        """Path of the newest COMMITTED checkpoint, or None."""
        ptr = os.path.join(self.run_dir, _LATEST)
        if not os.path.exists(ptr):
            return None
        with open(ptr) as f:
            name = f.read().strip()
        path = os.path.join(self.run_dir, name)
        return path if os.path.isdir(path) else None

    def restore(self, program, scope, ps_client=None) -> Optional[Dict]:
        """Restore the newest checkpoint into ``scope`` (and the PS
        tables through ``ps_client``); returns its cursor dict, or None
        when the run directory holds no committed checkpoint (fresh
        start)."""
        from paddle_tpu import io as _io

        path = self.latest()
        if path is None:
            return None
        _io.load_persistables(None, os.path.join(path, "params"),
                              main_program=program, scope=scope)
        ps_dir = os.path.join(path, "ps")
        if os.path.isdir(ps_dir):
            if ps_client is None:
                raise ValueError(
                    "checkpoint %s carries PS tables but no ps_client was "
                    "given to restore them" % path)
            self._restore_ps(ps_dir, ps_client)
        with open(os.path.join(path, "cursor.json")) as f:
            return json.load(f)

    @staticmethod
    def _restore_ps(dirname: str, ps_client) -> None:
        with open(os.path.join(dirname, "manifest.json")) as f:
            manifest = json.load(f)
        state = {}
        for ent in manifest["tables"]:
            i = int(ent["index"])
            ids = np.load(os.path.join(dirname, "t%03d_ids.npy" % i))
            rows = np.load(os.path.join(dirname, "t%03d_rows.npy" % i))
            mpath = os.path.join(dirname, "t%03d_moments.npy" % i)
            # pre-moments checkpoints (no flag, no file) restore as
            # before: rows only, accumulators restart
            if ent.get("moments") and os.path.exists(mpath):
                state[str(ent["table"])] = (ids, rows, np.load(mpath))
            else:
                state[str(ent["table"])] = (ids, rows)
        ps_client.load_tables(state)
