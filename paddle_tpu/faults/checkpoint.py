"""Crash-resumable training checkpoints.

The reference recovered a dead trainer by reloading persistables and a
PS table checkpoint (checkpoint_notify) and re-reading the dataset from
the top; this manager makes the recovery *exact to a step*: one
checkpoint atomically captures

1. the program's persistables (params + optimizer accumulators + LR),
   via ``io.save_persistables`` into the checkpoint directory,
2. the PS sparse tables (``PSClient.save`` row dump, restored by value
   with the ``assign`` op — not replayed through the optimizer), and
3. the dataset **cursor** (completed-step count + caller epoch), so a
   resumed ``train_from_dataset`` skips exactly the batches already
   consumed instead of restarting the epoch.

Atomicity is tmp+rename at every level: a checkpoint is staged under
``<run_dir>/.tmp-<step>``, ``os.replace``d to ``ckpt-<step>`` only when
complete, and only then does the ``LATEST`` pointer move (itself via
tmp+rename).  A SIGKILL at ANY instant leaves either the previous
checkpoint or the new one — never a half-written directory a resume
could trust.

**Async mode** (:meth:`save_async`) takes the save cost off the
training critical path: the caller (already quiesced) pays only a
copy-on-write gather — persistables copied to host numpy, PS tables
dumped by value — and serialization + commit happen on a background
snapshot thread.  The atomicity story is unchanged (the background
writer goes through the same tmp+rename commit), so a SIGKILL DURING a
background save leaves the previous committed checkpoint in charge;
the ``checkpoint.commit`` fault point injects delay/error into the
commit phase so chaos tests can pin exactly that window.  One save is
in flight at a time: a new ``save_async`` (or :meth:`wait`) joins the
previous one first.

**Shard-wise mode** (``save(..., compiled=<CompiledProgram>)``): a
mesh-sharded training run (``paddle_tpu.sharding.train``) must not
funnel every parameter and optimizer moment through one host buffer —
at scale the full tensor does not FIT one host.  With ``compiled=``
given, each mesh-committed persistable is saved as its **addressable
shards**: one ``.npy`` per distinct shard (replicas deduplicated) plus
a shard manifest recording the global shape, dtype, PartitionSpec, and
each shard's index slices.  No full tensor is ever materialized — the
per-shard files ARE the checkpoint (their shapes prove it).
Mesh-resident sparse tables (``sharding.sparse.MeshTableRuntime`` —
the program's ``_mesh_tables`` binding) ride the SAME path: row arrays
and optimizer moments dump shard-wise into ``shards/`` under manifest
entries tagged ``kind: mesh_table[_moments]`` and restore back into
the runtime.

**Topology-elastic restore** (``restore(..., compiled=)``): resume on
the SAME mesh re-places each saved shard straight onto its device via
``jax.make_array_from_single_device_arrays``.  Resume on a *different*
mesh shape, device assignment, or layout performs a **shard
exchange**: each target device's addressable region is assembled from
the OVERLAPPING saved shard files — slice-wise reads out of
memory-mapped per-shard ``.npy`` files, so the largest host buffer is
one device's region, never the full tensor (``last_restore_stats``
records the high-water mark).  :class:`CheckpointMeshMismatchError`
remains only for genuinely incompatible cases: a layout whose resolve
fails on the new mesh (axis divisibility), a global-shape drift, or
saved shards that no longer tile a target region (a doctored/partial
manifest).

**Integrity-verified recovery**: every committed checkpoint carries an
``integrity.json`` manifest — a content hash (sha256) and byte size
for EVERY file in the checkpoint (params, shards, PS tables, cursor).
``restore`` verifies the newest checkpoint before trusting it; a
flipped byte, truncation, or missing file is a typed
:class:`CheckpointCorruptionError`, counted in
``train_checkpoint_corruption_total``, and restore automatically falls
back through the keep-N chain to the newest fully-verifiable
checkpoint (each skip counted in ``train_checkpoint_fallback_total`` —
never silent).  A ``LATEST`` pointer naming a pruned/missing directory
falls back the same way instead of failing (or silently fresh-
starting) on the dangling pointer.  The ``checkpoint.restore`` fault
point arms the restore path for chaos drills exactly like
``checkpoint.commit`` arms the save path; ``tools/check_checkpoint.py``
runs the same verification offline.

Layout::

    run_dir/
      LATEST              # "ckpt-000040\n"
      ckpt-000040/
        cursor.json       # {"step": 40, "epoch": 0}
        integrity.json    # {"algo": "sha256", "files": {relpath:
                          #   {"sha256": ..., "bytes": ...}}} — every
                          #   other file in the checkpoint
        params/           # io.save_persistables output (host-resident
                          #   vars only in shard-wise mode)
        shards/           # optional: manifest.json + v<i>_s<j>.npy —
                          #   per-shard dumps of mesh-committed state
                          #   (incl. mesh-table rows/moments, tagged
                          #   kind: mesh_table[_moments])
        ps/               # optional: manifest.json + t<i>_{ids,rows}.npy
                          #   (+ t<i>_moments.npy: adagrad accumulators)
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

import paddle_tpu.faults as _faults
from paddle_tpu.faults.metrics import (
    TRAIN_CHECKPOINT_BYTES,
    TRAIN_CHECKPOINT_CORRUPTION,
    TRAIN_CHECKPOINT_FALLBACKS,
    TRAIN_CHECKPOINT_RESTORES,
    TRAIN_CHECKPOINTS,
)

__all__ = ["TrainCheckpoint", "CheckpointMeshMismatchError",
           "CheckpointCorruptionError", "verify_checkpoint_dir",
           "hash_file"]


class CheckpointMeshMismatchError(RuntimeError):
    """A shard-wise checkpoint is GENUINELY incompatible with the
    current mesh/layout: the layout cannot resolve on this mesh (axis
    divisibility), the global shape drifted, or the saved shards no
    longer tile a target device's region.  A merely *different* mesh
    shape or device assignment is NOT this error — the shard-exchange
    restore re-slices overlapping shards onto the new topology."""


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed integrity verification: a file listed in
    ``integrity.json`` is missing, truncated, or its content hash does
    not match what the commit recorded.  Restore falls back through
    the keep-N chain; this error surfaces only when NO checkpoint in
    the run directory verifies."""


def hash_file(path: str, chunk: int = 1 << 20) -> str:
    """sha256 hex digest of a file, streamed (checkpoints can exceed
    comfortable read-at-once sizes)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(chunk), b""):
            h.update(block)
    return h.hexdigest()


def verify_checkpoint_dir(path: str) -> None:
    """Verify one committed checkpoint directory against its
    ``integrity.json``: every listed file must exist with the recorded
    size and content hash, and every file on disk must be listed (an
    unlisted file means the directory was tampered with after the
    commit).  Raises :class:`CheckpointCorruptionError`; checkpoints
    from before the integrity manifest existed pass unverified (there
    is nothing to check them against)."""
    integ = os.path.join(path, _INTEGRITY)
    if not os.path.exists(integ):
        return  # pre-integrity checkpoint: restore-as-before semantics
    try:
        with open(integ) as f:
            doc = json.load(f)
        files = dict(doc["files"])
        entries = sorted((str(rel), ent["sha256"], int(ent["bytes"]))
                         for rel, ent in files.items())
    except CheckpointCorruptionError:
        raise
    except Exception as e:  # noqa: BLE001 — ANY malformed-structure
        # shape (non-dict files, entry missing a key, junk types) must
        # become the typed corruption so the keep-N fallback engages —
        # an untyped crash here would defeat the recovery chain
        raise CheckpointCorruptionError(
            "checkpoint %s: unreadable/malformed integrity manifest (%s)"
            % (path, e)) from None
    on_disk = set()
    for dirpath, _, fns in os.walk(path):
        for fn in fns:
            rel = os.path.relpath(os.path.join(dirpath, fn), path)
            if rel != _INTEGRITY:
                on_disk.add(rel.replace(os.sep, "/"))
    listed = {rel for rel, _, _ in entries}
    for rel in sorted(listed - on_disk):
        raise CheckpointCorruptionError(
            "checkpoint %s: file %r listed in the integrity manifest "
            "is missing" % (path, rel))
    for rel in sorted(on_disk - listed):
        raise CheckpointCorruptionError(
            "checkpoint %s: file %r on disk is not in the integrity "
            "manifest (written after the commit?)" % (path, rel))
    for rel, want_digest, want_bytes in entries:
        fpath = os.path.join(path, *rel.split("/"))
        size = os.path.getsize(fpath)
        if size != want_bytes:
            raise CheckpointCorruptionError(
                "checkpoint %s: file %r is %d bytes, manifest recorded "
                "%d (truncated?)" % (path, rel, size, want_bytes))
        digest = hash_file(fpath)
        if digest != want_digest:
            raise CheckpointCorruptionError(
                "checkpoint %s: file %r content hash %s does not match "
                "the recorded %s (corrupted on disk)"
                % (path, rel, digest, want_digest))


def _load_shard(fpath: str, mmap_mode=None) -> np.ndarray:
    """``np.load`` with unreadable/truncated content re-typed as
    :class:`CheckpointCorruptionError` — a damaged PRE-integrity
    checkpoint (nothing for the hash gate to check) must still engage
    restore's fallback chain, never an untyped crash."""
    try:
        return np.load(fpath, mmap_mode=mmap_mode)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptionError(
            "shard file %s is unreadable (%s)" % (fpath, e)) from None


def _index_key(index, shape) -> Tuple[Tuple[int, int], ...]:
    """Normalize a shard index (tuple of slices over the global shape)
    to a hashable/JSON-safe ((start, stop), ...) key."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)

_LATEST = "LATEST"
_PREFIX = "ckpt-"
_TMP_PREFIX = ".tmp-"
_INTEGRITY = "integrity.json"


class TrainCheckpoint:
    """One run directory's checkpoint manager.

    ``every_n_steps``: cadence for :meth:`should_save` (0 disables the
    periodic trigger; explicit :meth:`save` always works).
    ``keep``: finished checkpoints retained (older ones are pruned
    after each successful commit; the latest is never pruned).
    """

    def __init__(self, run_dir: str, every_n_steps: int = 0, keep: int = 2):
        self.run_dir = str(run_dir)
        self.every_n_steps = int(every_n_steps)
        self.keep = max(1, int(keep))
        self._bg: Optional[threading.Thread] = None
        self._bg_result: Optional[str] = None
        self._bg_error: Optional[BaseException] = None
        # restore bookkeeping (read by the executor and the drills):
        # which checkpoint actually restored, how many were skipped on
        # the way there, and the shard-exchange host-buffer high-water
        self.last_restore_path: Optional[str] = None
        self.last_restore_fallbacks: int = 0
        self.last_restore_stats: Optional[Dict] = None
        os.makedirs(self.run_dir, exist_ok=True)

    # ------------------------------------------------------------------
    def should_save(self, completed_steps: int) -> bool:
        return (self.every_n_steps > 0
                and completed_steps > 0
                and completed_steps % self.every_n_steps == 0)

    def _name(self, step: int) -> str:
        return "%s%06d" % (_PREFIX, int(step))

    # ------------------------------------------------------------------
    def save(self, program, scope, step: int, epoch: int = 0,
             ps_client=None, extra: Optional[Dict] = None,
             compiled=None) -> str:
        """Commit one checkpoint; returns the finished directory path.
        ``step`` is the number of COMPLETED steps (the resume cursor).
        ``compiled``: the CompiledProgram a sharded training run
        executes through — mesh-committed state then saves SHARD-wise
        (each device's addressable shards, never a gathered full
        tensor).  The caller is responsible for quiescing async state
        first (the executor joins its overlapped PS pull and flushes
        the Communicator before calling)."""
        self.wait()  # never interleave with an in-flight async save
        ps_state = (self._gather_ps(ps_client)
                    if ps_client is not None else None)
        shard_state = self._gather_shards(program, scope, compiled,
                                          copy=False)
        return self._commit(program, scope, step, epoch, ps_state, extra,
                            shard_state)

    def save_async(self, program, scope, step: int, epoch: int = 0,
                   ps_client=None, extra: Optional[Dict] = None,
                   compiled=None) -> None:
        """Snapshot now, serialize in the background.

        The caller-thread cost is one copy-on-write gather: every
        persistable's value copied to host numpy (into a detached
        snapshot scope; mesh-committed state copies PER SHARD — the
        full tensor is never materialized) and the PS tables dumped by
        value — the PS sockets are only touched here, never from the
        writer thread.  Serialization, fsync traffic, the tmp+rename
        commit, and pruning all happen on a daemon snapshot thread;
        training continues immediately.  A previous in-flight save is
        joined first (its error, if any, re-raises HERE — a silent
        checkpoint gap must not go unnoticed); call :meth:`wait` at end
        of epoch to commit the tail save."""
        self.wait()
        shard_state = self._gather_shards(program, scope, compiled,
                                          copy=True)
        exclude = set(shard_state["vars"]) if shard_state else ()
        snap = self._snapshot_scope(program, scope, exclude=exclude)
        ps_state = (self._gather_ps(ps_client)
                    if ps_client is not None else None)
        self._bg_result = self._bg_error = None

        def _write():
            try:
                self._bg_result = self._commit(
                    program, snap, step, epoch, ps_state, extra,
                    shard_state)
            except BaseException as e:  # noqa: BLE001 — re-raised at wait()
                self._bg_error = e

        self._bg = threading.Thread(
            target=_write, name="ckpt-writer-%06d" % int(step), daemon=True)
        self._bg.start()

    def wait(self, timeout: Optional[float] = None) -> Optional[str]:
        """Join the in-flight background save, if any.  Returns its
        committed path (None when nothing was in flight) and re-raises
        its failure."""
        bg, self._bg = self._bg, None
        if bg is not None:
            bg.join(timeout)
            if bg.is_alive():  # caller keeps ownership of the join
                self._bg = bg
                raise TimeoutError("background checkpoint still writing")
        if self._bg_error is not None:
            err, self._bg_error = self._bg_error, None
            raise err
        result, self._bg_result = self._bg_result, None
        return result

    @property
    def in_flight(self) -> bool:
        return self._bg is not None and self._bg.is_alive()

    @staticmethod
    def _snapshot_scope(program, scope, exclude=()):
        """Copy every persistable's current value into a detached
        scope: the writer thread reads ONLY these copies, so training
        may mutate the live scope the instant this returns.
        ``exclude``: names captured elsewhere (the shard-wise gather) —
        copying them here would materialize the full tensor."""
        from paddle_tpu import io as _io
        from paddle_tpu.scope import Scope

        snap = Scope()
        for v in _io._collect(program, _io._is_persistable, None):
            if v.name in exclude:
                continue
            val = scope.get(v.name)
            if val is not None:
                snap.set(v.name, np.array(np.asarray(val), copy=True))
        return snap

    @staticmethod
    def _shard_entry(val, copy: bool, extra: Optional[Dict] = None
                     ) -> Dict:
        """One manifest entry for a mesh-committed array: per-shard
        host copies deduplicated by index (a replica is skipped — each
        shard copies only ITS slice, the full tensor never exists in
        one buffer).  ``copy=True`` (async mode) forces an owned numpy
        copy so a donated device buffer mutated by the next step cannot
        reach the writer thread."""
        from paddle_tpu.sharding.rules import spec_to_manifest

        shape = tuple(int(d) for d in val.shape)
        seen: Dict[Tuple, np.ndarray] = {}
        for s in val.addressable_shards:
            key = _index_key(s.index, shape)
            if key in seen:
                continue  # a replica of an already-captured shard
            arr = np.asarray(s.data)  # THIS shard only, never full
            if copy:
                arr = np.array(arr, copy=True)
            seen[key] = arr
        spec = getattr(val.sharding, "spec", None)
        entry = {
            "shape": shape,
            "dtype": str(val.dtype),
            "spec": spec_to_manifest(spec) if spec is not None else None,
            "shards": sorted(seen.items()),
        }
        if extra:
            entry.update(extra)
        return entry

    @staticmethod
    def _gather_shards(program, scope, compiled, copy: bool):
        """Collect mesh-committed persistables (and any bound
        mesh-table runtime's rows/moments) as per-shard host arrays.
        Returns None when ``compiled`` is None or nothing is
        mesh-committed."""
        if compiled is None:
            return None
        from paddle_tpu import io as _io

        mesh = compiled.mesh
        mesh_axes = {str(a): int(n) for a, n in
                     zip(mesh.axis_names, mesh.devices.shape)}
        entries: Dict[str, Dict] = {}
        for v in _io._collect(program, _io._is_persistable, None):
            val = scope.get(v.name)
            sh = getattr(val, "sharding", None)
            if (not getattr(val, "addressable_shards", None) or sh is None
                    or len(getattr(sh, "device_set", ())) <= 1):
                continue  # host / single-device value: params/ path
            if getattr(sh, "is_fully_replicated", False):
                # every device holds the FULL value (plain data-parallel
                # state, norms/LR under a sharded layout): the params/
                # path saves one portable host copy — routing it through
                # shards/ would pin a replicated checkpoint to this
                # mesh's exact shape for zero space win
                continue
            entries[v.name] = TrainCheckpoint._shard_entry(val, copy)
        # mesh-resident sparse tables (sharding.sparse): rows + moments
        # live as sharded device arrays on the runtime, not in the
        # scope — dump them shard-wise through the same manifest,
        # tagged so restore routes them back into the runtime
        runtime = getattr(program, "_mesh_tables", None)
        if runtime is not None:
            for ename, ent in runtime.checkpoint_state().items():
                entries[ename] = TrainCheckpoint._shard_entry(
                    ent["array"], copy,
                    extra={"kind": ent["kind"], "table": ent["table"],
                           "height": int(ent["height"])})
        if not entries:
            return None
        return {"mesh_axes": mesh_axes, "vars": entries}

    def _commit(self, program, scope, step, epoch, ps_state, extra,
                shard_state=None) -> str:
        """The write + atomic-rename phase (caller thread for ``save``,
        snapshot thread for ``save_async``); reads only the given scope
        and the pre-gathered ``ps_state``/``shard_state``."""
        from paddle_tpu import io as _io

        final = os.path.join(self.run_dir, self._name(step))
        tmp = os.path.join(self.run_dir, _TMP_PREFIX + self._name(step))
        for stale in (tmp, final):  # a crashed previous attempt
            if os.path.isdir(stale):
                shutil.rmtree(stale)
        os.makedirs(tmp)
        shard_names = set(shard_state["vars"]) if shard_state else set()
        _io.save_vars(
            None, os.path.join(tmp, "params"), main_program=program,
            predicate=lambda v: (_io._is_persistable(v)
                                 and v.name not in shard_names),
            scope=scope)
        if shard_state is not None:
            self._write_shards(os.path.join(tmp, "shards"), shard_state)
        if ps_state is not None:
            self._write_ps(os.path.join(tmp, "ps"), ps_state)
        cursor = {"step": int(step), "epoch": int(epoch)}
        if extra:
            cursor.update(extra)
        with open(os.path.join(tmp, "cursor.json"), "w") as f:
            json.dump(cursor, f)
        total_bytes = self._write_integrity(tmp)
        if _faults.active is not None:  # disarmed: one is-None gate
            # the chaos window: a kill/delay/error HERE lands between a
            # fully staged tmp dir and its commit — resume must still
            # see only the previous committed checkpoint
            _faults.active.faultpoint(
                "checkpoint.commit", run_dir=self.run_dir, step=int(step))
        os.replace(tmp, final)
        TRAIN_CHECKPOINT_BYTES.set(total_bytes)
        # move LATEST only after the checkpoint directory is committed
        ptr_tmp = os.path.join(self.run_dir, _LATEST + ".tmp")
        with open(ptr_tmp, "w") as f:
            f.write(self._name(step) + "\n")
        os.replace(ptr_tmp, os.path.join(self.run_dir, _LATEST))
        TRAIN_CHECKPOINTS.inc()
        self._prune(keep_name=self._name(step))
        return final

    @staticmethod
    def _write_integrity(tmp: str) -> int:
        """Hash every staged file into ``integrity.json`` (the LAST
        file written before the commit rename, so it covers all the
        others); returns the checkpoint's total byte size."""
        files: Dict[str, Dict] = {}
        total = 0
        for dirpath, _, fns in os.walk(tmp):
            for fn in sorted(fns):
                p = os.path.join(dirpath, fn)
                rel = os.path.relpath(p, tmp).replace(os.sep, "/")
                size = os.path.getsize(p)
                files[rel] = {"sha256": hash_file(p), "bytes": size}
                total += size
        with open(os.path.join(tmp, _INTEGRITY), "w") as f:
            json.dump({"algo": "sha256", "files": files}, f)
        return total + os.path.getsize(os.path.join(tmp, _INTEGRITY))

    @staticmethod
    def _write_shards(dirname: str, shard_state) -> None:
        """One ``.npy`` per distinct shard plus a manifest tying each
        file to its variable, global shape/dtype/spec, and index
        slices.  File shapes ARE shard shapes — the on-disk proof that
        no full tensor was gathered."""
        os.makedirs(dirname)
        manifest = {"mesh_axes": shard_state["mesh_axes"], "vars": {}}
        for i, (name, ent) in enumerate(sorted(
                shard_state["vars"].items())):
            files = []
            for j, (key, arr) in enumerate(ent["shards"]):
                fname = "v%03d_s%02d.npy" % (i, j)
                np.save(os.path.join(dirname, fname), arr)
                files.append({"file": fname,
                              "index": [list(se) for se in key]})
            doc = {
                "shape": list(ent["shape"]),
                "dtype": ent["dtype"],
                "spec": ent["spec"],
                "shards": files,
            }
            for extra in ("kind", "table", "height"):
                if extra in ent:
                    doc[extra] = ent[extra]
            manifest["vars"][name] = doc
        with open(os.path.join(dirname, "manifest.json"), "w") as f:
            json.dump(manifest, f)

    @staticmethod
    def _gather_ps(ps_client):
        # include_moments: the adagrad accumulators dump alongside the
        # rows so a SIGKILL-resume is exact for sparse optimizers (a
        # moment-less restore would restart per-row step sizes at their
        # largest and re-diverge the loss trajectory)
        return ps_client.save(include_moments=True)

    @staticmethod
    def _write_ps(dirname: str, state) -> None:
        os.makedirs(dirname)
        manifest = []
        for i, (table, value) in enumerate(sorted(state.items())):
            ids, rows = value[0], value[1]
            moments = value[2] if len(value) == 3 else None
            np.save(os.path.join(dirname, "t%03d_ids.npy" % i),
                    np.asarray(ids, np.int64))
            np.save(os.path.join(dirname, "t%03d_rows.npy" % i),
                    np.asarray(rows, np.float32))
            if moments is not None:
                np.save(os.path.join(dirname, "t%03d_moments.npy" % i),
                        np.asarray(moments, np.float32))
            manifest.append({"table": table, "index": i,
                             "dim": int(rows.shape[1]) if rows.size else 0,
                             "moments": moments is not None})
        with open(os.path.join(dirname, "manifest.json"), "w") as f:
            json.dump({"tables": manifest}, f)

    @staticmethod
    def _step_of(name: str) -> int:
        try:
            return int(name[len(_PREFIX):])
        except ValueError:
            return -1

    def _prune(self, keep_name: str) -> None:
        # numeric order, not lexicographic: a step past the %06d padding
        # must never make a NEWER checkpoint sort as the oldest
        done = sorted(
            (d for d in os.listdir(self.run_dir)
             if d.startswith(_PREFIX)
             and os.path.isdir(os.path.join(self.run_dir, d))),
            key=self._step_of)
        excess = [d for d in done[:-self.keep] if d != keep_name]
        for d in excess:
            shutil.rmtree(os.path.join(self.run_dir, d), ignore_errors=True)

    # ------------------------------------------------------------------
    def latest(self) -> Optional[str]:
        """Path of the checkpoint the ``LATEST`` pointer names, or None
        when there is no pointer or its target is gone.  :meth:`restore`
        does NOT stop here — a dangling pointer falls back through the
        remaining complete checkpoints (counted)."""
        ptr = os.path.join(self.run_dir, _LATEST)
        if not os.path.exists(ptr):
            return None
        with open(ptr) as f:
            name = f.read().strip()
        path = os.path.join(self.run_dir, name)
        return path if os.path.isdir(path) else None

    def _completed(self) -> List[str]:
        """Committed checkpoint directory names, NEWEST first."""
        return sorted(
            (d for d in os.listdir(self.run_dir)
             if d.startswith(_PREFIX)
             and os.path.isdir(os.path.join(self.run_dir, d))),
            key=self._step_of, reverse=True)

    def restore(self, program, scope, ps_client=None,
                compiled=None) -> Optional[Dict]:
        """Restore the newest VERIFIABLE checkpoint into ``scope`` (and
        the PS tables through ``ps_client``, and any bound mesh-table
        runtime); returns its cursor dict, or None when the run
        directory holds no committed checkpoint (fresh start).

        Integrity first: each candidate is verified against its
        ``integrity.json`` before anything loads — a corrupt/truncated
        checkpoint (or a ``LATEST`` pointer naming a pruned directory)
        falls back to the next-newest complete one, counted in
        ``train_checkpoint_fallback_total`` /
        ``train_checkpoint_corruption_total``; only when NO candidate
        verifies does the :class:`CheckpointCorruptionError` surface.

        A shard-wise checkpoint needs ``compiled`` (the run's sharded
        layout).  The mesh does NOT have to match the saving one: a
        different shape or device assignment restores through the
        shard-exchange path (each device's region assembled from the
        overlapping saved shard files, slice-wise).  Genuinely
        incompatible specs — a layout that cannot resolve on the new
        mesh, shape drift, shards that no longer tile a region — stay a
        typed :class:`CheckpointMeshMismatchError` and do NOT fall
        back (they are configuration errors, not disk corruption)."""
        self.last_restore_path = None
        self.last_restore_fallbacks = 0
        self.last_restore_stats = None
        names = self._completed()
        ptr = os.path.join(self.run_dir, _LATEST)
        pointed = None
        if os.path.exists(ptr):
            with open(ptr) as f:
                pointed = f.read().strip()
        if not names:
            if pointed:
                # the pointer is on-disk evidence committed state
                # EXISTED; with every checkpoint directory gone this is
                # a loss, not a fresh start — never restart from step 0
                # silently
                TRAIN_CHECKPOINT_CORRUPTION.inc()
                raise CheckpointCorruptionError(
                    "run dir %s: LATEST names %r but no committed "
                    "checkpoint directory remains — the run's state "
                    "was lost (bad prune / partial disk restore?)"
                    % (self.run_dir, pointed))
            return None
        if pointed and pointed not in names:
            # dangling pointer (its target was pruned or lost): the
            # newest complete checkpoint serves instead — counted,
            # never a silent fresh start
            TRAIN_CHECKPOINT_FALLBACKS.inc()
            self.last_restore_fallbacks += 1
        last_err: Optional[CheckpointCorruptionError] = None
        for i, name in enumerate(names):
            path = os.path.join(self.run_dir, name)
            if _faults.active is not None:  # disarmed: one is-None gate
                # the restore-side chaos window (mirrors
                # checkpoint.commit on the save side): delay/error here
                # lands between picking a candidate and trusting it
                _faults.active.faultpoint(
                    "checkpoint.restore", run_dir=self.run_dir, path=path)
            try:
                verify_checkpoint_dir(path)
                cursor = self._restore_one(path, program, scope,
                                           ps_client, compiled)
            except CheckpointCorruptionError as e:
                # a pre-integrity checkpoint (nothing to verify against)
                # can still fail at LOAD time — _restore_one types its
                # unreadable-file failures so the fallback engages for
                # them too; the scope may be partially written, but the
                # next candidate's load overwrites every name it set
                TRAIN_CHECKPOINT_CORRUPTION.inc()
                last_err = e
                if i + 1 < len(names):
                    TRAIN_CHECKPOINT_FALLBACKS.inc()
                    self.last_restore_fallbacks += 1
                continue
            TRAIN_CHECKPOINT_RESTORES.inc()
            self.last_restore_path = path
            return cursor
        raise last_err  # every candidate failed verification

    def _restore_one(self, path: str, program, scope, ps_client,
                     compiled) -> Dict:
        """Load one verified checkpoint directory (params + shards +
        PS tables + cursor).  Unreadable/truncated file content
        re-raises as :class:`CheckpointCorruptionError` (restore's
        fallback class); configuration errors (missing ps_client /
        compiled / mesh-table binding, mesh incompatibility) keep
        their own types and do NOT fall back."""
        from paddle_tpu import io as _io

        try:
            _io.load_persistables(None, os.path.join(path, "params"),
                                  main_program=program, scope=scope)
        except (OSError, ValueError, KeyError) as e:
            raise CheckpointCorruptionError(
                "checkpoint %s: params failed to load (%s)"
                % (path, e)) from None
        shards_dir = os.path.join(path, "shards")
        if os.path.isdir(shards_dir):
            self.last_restore_stats = self._restore_shards(
                shards_dir, scope, compiled, program)
        ps_dir = os.path.isdir(os.path.join(path, "ps"))
        if ps_dir:
            if ps_client is None:
                raise ValueError(
                    "checkpoint %s carries PS tables but no ps_client was "
                    "given to restore them" % path)
            self._restore_ps(os.path.join(path, "ps"), ps_client)
        if ps_dir or (self.last_restore_stats or {}).get("mesh_tables"):
            cache = getattr(program, "_embedding_cache", None)
            if cache is not None:
                # the restore rewrote rows wholesale (server-side or on
                # the mesh): a cached copy from before it is stale
                # (regression-pinned in tests/test_embedding_cache.py)
                cache.invalidate()
        try:
            with open(os.path.join(path, "cursor.json")) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointCorruptionError(
                "checkpoint %s: unreadable cursor.json (%s)"
                % (path, e)) from None

    # ------------------------------------------------------------------
    # shard-exchange restore
    # ------------------------------------------------------------------
    def _restore_shards(self, dirname: str, scope, compiled,
                        program=None) -> Dict:
        """Re-place saved shards onto the CURRENT mesh/layout.

        Same-topology fast path: a target region that exactly matches a
        saved shard loads its file whole.  Different topology (mesh
        shape, device assignment, or layout): each target device's
        region is ASSEMBLED from the overlapping saved shard files —
        slice-wise reads out of memory-mapped ``.npy`` files, so the
        largest host buffer is one device's region (tracked in the
        returned stats as ``max_region_bytes``); the full tensor is
        never materialized on any host, in either direction.

        Typed :class:`CheckpointMeshMismatchError` only for the
        genuinely incompatible: the layout cannot resolve on this mesh
        (axis divisibility), the global shape drifted from the program,
        or the saved shards do not tile a required region."""
        import jax

        try:
            with open(os.path.join(dirname, "manifest.json")) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointCorruptionError(
                "checkpoint shards manifest %s is unreadable (%s)"
                % (os.path.join(dirname, "manifest.json"), e)) from None
        stats = {"direct": 0, "exchanged": 0, "regions": 0,
                 "shard_files_read": 0, "max_region_bytes": 0,
                 "mesh_tables": 0}
        runtime = (getattr(program, "_mesh_tables", None)
                   if program is not None else None)
        for name, ent in manifest["vars"].items():
            shape = tuple(int(d) for d in ent["shape"])
            dtype = np.dtype(ent["dtype"])
            saved = []
            for doc in ent["shards"]:
                box = tuple(tuple(int(x) for x in se)
                            for se in doc["index"])
                saved.append((box, os.path.join(dirname, doc["file"])))
            if ent.get("kind") in ("mesh_table", "mesh_table_moments",
                                   "mesh_table_scales"):
                self._restore_mesh_table(name, ent, saved, shape, dtype,
                                         runtime, stats)
                continue
            if compiled is None:
                raise ValueError(
                    "checkpoint %s holds SHARD-wise state — pass the "
                    "run's CompiledProgram (compiled=) so shards "
                    "re-place onto its mesh" % os.path.dirname(dirname))
            try:
                sharding = compiled.state_sharding(name)
            except Exception as e:
                # e.g. a dim no longer divisible by the new mesh's axis
                # size — the one genuinely spec-incompatible resume
                raise CheckpointMeshMismatchError(
                    "var %r: the current layout cannot resolve on this "
                    "mesh (%s) — the checkpoint itself is fine; fix the "
                    "layout or resume on a compatible mesh"
                    % (name, e)) from None
            var = (program.global_block()._find_var_recursive(name)
                   if program is not None else None)
            if (var is not None and var.shape is not None
                    and -1 not in tuple(var.shape)
                    and tuple(int(d) for d in var.shape) != shape):
                raise CheckpointMeshMismatchError(
                    "var %r was saved with global shape %s but the "
                    "program declares %s — the model changed since the "
                    "checkpoint" % (name, shape, tuple(var.shape)))
            scope.set(name, self._exchange_place(
                jax, name, shape, dtype, sharding, saved, stats))
        return stats

    def _exchange_place(self, jax, name, shape, dtype, sharding, saved,
                        stats, required_rows=None):
        """Assemble every distinct target region of ``sharding`` over
        ``shape`` from the saved shard files and place it per device;
        returns the committed global array."""
        from paddle_tpu.sharding.train import box_overlap, shard_boxes

        # the coverage check below sums overlap volumes, which is exact
        # ONLY over a disjoint shard grid — and these boxes come from an
        # untrusted manifest.  A duplicate/overlapping entry could fake
        # full coverage while leaving zero-filled holes.
        for i in range(len(saved)):
            for j in range(i + 1, len(saved)):
                if box_overlap(saved[i][0], saved[j][0]) is not None:
                    raise CheckpointMeshMismatchError(
                        "var %r: saved shard indexes %s and %s overlap "
                        "— a PartitionSpec shard grid is disjoint, so "
                        "the manifest was doctored or mis-written"
                        % (name, saved[i][0], saved[j][0]))
        arrays = []
        for box, devs in shard_boxes(sharding, shape).items():
            stats["regions"] += 1
            if required_rows is None:
                required = box
            else:
                required = box_overlap(
                    box, ((0, int(required_rows)),)
                    + tuple((0, int(d)) for d in shape[1:]))
            arr = self._assemble_region(name, box, dtype, saved,
                                        required, stats)
            for dev in devs:
                arrays.append(jax.device_put(arr, dev))
        return jax.make_array_from_single_device_arrays(
            shape, sharding, arrays)

    @staticmethod
    def _assemble_region(name, box, dtype, saved, required, stats):
        """One target region: the exact-match fast path loads the saved
        file whole; otherwise overlapping saved shards are slice-read
        (mmap) into a region-sized buffer.  ``required`` (a sub-box of
        ``box``, or None for none) must be fully tiled by the saved
        shards — cells outside it (mesh-table padding rows) zero-fill."""
        from paddle_tpu.sharding.train import box_overlap, box_volume

        for sbox, fpath in saved:
            if sbox == box:
                arr = _load_shard(fpath)  # shard-sized, never the full tensor
                stats["direct"] += 1
                stats["shard_files_read"] += 1
                stats["max_region_bytes"] = max(
                    stats["max_region_bytes"], int(arr.nbytes))
                return arr
        buf = np.zeros(tuple(hi - lo for lo, hi in box), dtype)
        covered = 0
        for sbox, fpath in saved:
            ov = box_overlap(sbox, box)
            if ov is None:
                continue
            src = _load_shard(fpath, mmap_mode="r")  # slice-wise read only
            src_sl = tuple(slice(lo - s[0], hi - s[0])
                           for (lo, hi), s in zip(ov, sbox))
            dst_sl = tuple(slice(lo - b[0], hi - b[0])
                           for (lo, hi), b in zip(ov, box))
            buf[dst_sl] = src[src_sl]
            stats["shard_files_read"] += 1
            if required is not None:
                req_ov = box_overlap(ov, required)
                if req_ov is not None:
                    covered += box_volume(req_ov)
        if required is not None and covered != box_volume(required):
            raise CheckpointMeshMismatchError(
                "var %r: the saved shards cover only %d of %d cells of "
                "target region %s — the checkpoint's shard set is "
                "incomplete for this layout/mesh (doctored manifest, or "
                "a partial save)" % (name, covered,
                                     box_volume(required), box))
        stats["exchanged"] += 1
        stats["max_region_bytes"] = max(
            stats["max_region_bytes"], int(buf.nbytes))
        return buf

    def _restore_mesh_table(self, name, ent, saved, saved_shape, dtype,
                            runtime, stats) -> None:
        """Route a ``kind: mesh_table[_moments]`` manifest entry back
        into the bound :class:`MeshTableRuntime` — the same exchange
        step, with the CURRENT padded height as the target shape (row
        padding differs across shard counts; rows past the real height
        are never read by a lookup and zero-fill)."""
        import jax

        table = str(ent.get("table", name))
        kind = str(ent["kind"])
        if runtime is None or table not in getattr(runtime, "tables", {}):
            raise ValueError(
                "checkpoint entry %r is mesh-table state for table %r "
                "but the program has no mesh-table runtime binding it — "
                "bind_mesh_tables(...) on the run's CompiledProgram "
                "before restoring" % (name, table))
        tbl = runtime.tables[table]
        if kind == "mesh_table_moments" and tbl.moments is None:
            return  # saved adagrad moments, runtime runs sgd: unused
        if kind == "mesh_table_scales":
            target = getattr(tbl, "scales", None)
            if target is None:
                # int8-row checkpoint restored into an fp32 runtime —
                # a dtype mismatch, not a mesh problem: name the fix
                raise CheckpointMeshMismatchError(
                    "mesh table %r: checkpoint carries int8 row scales "
                    "but the runtime stores fp32 rows — rebind with "
                    "bind_mesh_tables(row_dtype='int8') to restore this "
                    "checkpoint" % table)
        else:
            target = (tbl.moments if kind == "mesh_table_moments"
                      else tbl.array)
        cur_shape = tuple(int(d) for d in target.shape)
        if tuple(saved_shape[1:]) != tuple(cur_shape[1:]):
            raise CheckpointMeshMismatchError(
                "mesh table %r: saved row shape %s vs the runtime's %s "
                "— the table changed since the checkpoint"
                % (table, saved_shape[1:], cur_shape[1:]))
        height = int(ent.get("height", saved_shape[0]))
        if height != tbl.height:
            raise CheckpointMeshMismatchError(
                "mesh table %r: saved height %d vs the runtime's %d — "
                "the table changed since the checkpoint"
                % (table, height, tbl.height))
        runtime.install_state(table, kind, self._exchange_place(
            jax, name, cur_shape, dtype, target.sharding, saved, stats,
            required_rows=min(height, int(saved_shape[0]))))
        stats["mesh_tables"] += 1

    @staticmethod
    def _restore_ps(dirname: str, ps_client) -> None:
        try:
            with open(os.path.join(dirname, "manifest.json")) as f:
                manifest = json.load(f)
            tables = [(int(ent["index"]), str(ent["table"]),
                       bool(ent.get("moments"))) for ent in
                      manifest["tables"]]
        except (OSError, ValueError, KeyError, TypeError) as e:
            raise CheckpointCorruptionError(
                "checkpoint PS manifest %s is unreadable/malformed (%s)"
                % (os.path.join(dirname, "manifest.json"), e)) from None
        state = {}
        for i, table, has_moments in tables:
            ids = _load_shard(os.path.join(dirname, "t%03d_ids.npy" % i))
            rows = _load_shard(os.path.join(dirname, "t%03d_rows.npy" % i))
            mpath = os.path.join(dirname, "t%03d_moments.npy" % i)
            # pre-moments checkpoints (no flag, no file) restore as
            # before: rows only, accumulators restart
            if has_moments and os.path.exists(mpath):
                state[table] = (ids, rows, _load_shard(mpath))
            else:
                state[table] = (ids, rows)
        ps_client.load_tables(state)
