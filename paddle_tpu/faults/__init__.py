"""Deterministic, process-global fault injection.

The reference stack's fault-tolerance story (bounded RPC retry with
deadlines in grpc_client.cc, checkpoint-notify for PS tables, trainer
restart from persistables) was only ever exercised by real outages; the
TPU-native rebuild injects the failures on purpose.  A **fault point**
is a named site in a real code path::

    # at the site (hot path: one is-None gate when disarmed)
    from paddle_tpu import faults as _faults
    ...
    if _faults.active is not None:
        _faults.active.faultpoint("wire.send")

and a **plan** is a seeded, declarative list of what each point should
do when hit: raise a typed error, sleep, corrupt bytes (the caller
applies the returned action), drop the first N hits then heal, or
SIGKILL a child process whose pid the site passes.  Armed via the API
(:func:`arm` / :func:`armed`) or the ``PADDLE_TPU_FAULTS`` env var so a
launched child process can arrive pre-armed::

    PADDLE_TPU_FAULTS="wire.send=corrupt,times=2;ps.pull=delay:0.05"

Contracts the rest of the framework relies on:

* **Disarmed cost is one is-None gate.**  ``faults.active`` is a plain
  module attribute, ``None`` unless a plan is armed; no function call,
  no lock, no lookup happens on the disarmed path (the <1% executor
  idle-overhead bound and ``tools/check_hot_path.py`` both still hold).
* **Determinism.**  Every probabilistic decision draws from a
  ``random.Random`` seeded from ``(plan seed, point name)``; two plans
  with the same seed fire identically.  Counters (``after``/``times``)
  are exact, under one lock.
* **Observability.**  Every fired injection increments
  ``faults_injected_total{point=...}`` and the plan's own
  ``triggers()`` dict, so a chaos test can assert exactly what landed.

The catalog of fault points threaded through the framework lives in
the README ("Fault tolerance" section); ``tools/check_fault_points.py``
holds source, docs, and the chaos suite to the same set.
"""
from __future__ import annotations

import os
import random
import re
import signal
import threading
import time
from typing import Dict, List, Optional, Sequence

from paddle_tpu.faults.metrics import FAULTS_INJECTED

__all__ = [
    "FaultSpec", "FaultAction", "FaultPlan",
    "arm", "disarm", "armed", "arm_from_env", "parse_plan",
    "active",
]

# the one global the gates check: None = disarmed (never a stale plan)
active: Optional["FaultPlan"] = None

_POINT_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$")

_MODES = ("error", "delay", "corrupt", "kill")


def _resolve_error(name: str):
    """Error-type lookup for ``error:`` specs: the typed serving errors
    first, then a small builtin whitelist — never eval."""
    from paddle_tpu.serving import errors as _serr

    if hasattr(_serr, name):
        return getattr(_serr, name)
    builtin = {
        "RuntimeError": RuntimeError,
        "ValueError": ValueError,
        "ConnectionError": ConnectionError,
        "ConnectionResetError": ConnectionResetError,
        "TimeoutError": TimeoutError,
        "OSError": OSError,
        "IOError": IOError,
    }
    if name in builtin:
        return builtin[name]
    raise ValueError("unknown fault error type %r" % name)


class FaultSpec:
    """One declarative injection: WHAT happens at WHICH point, WHEN.

    ``mode``: ``error`` (raise ``error_type``), ``delay`` (sleep
    ``delay_s`` then continue), ``corrupt`` (return a
    :class:`FaultAction` the site applies to its bytes), ``kill``
    (SIGKILL the pid the site passed as context).
    ``after``: skip the first N hits of the point (arm mid-traffic).
    ``times``: fire at most N times, then heal (drop-N-then-heal).
    ``prob``: fire with this seeded probability per eligible hit.
    """

    __slots__ = ("point", "mode", "error_type", "delay_s", "after",
                 "times", "prob", "message", "hits", "fired")

    def __init__(self, point: str, mode: str,
                 error: str = "BackendUnavailable",
                 delay_s: float = 0.0,
                 after: int = 0, times: Optional[int] = None,
                 prob: float = 1.0, message: Optional[str] = None):
        if not _POINT_RE.match(point):
            raise ValueError("invalid fault point name %r" % point)
        if mode not in _MODES:
            raise ValueError("fault mode %r not in %s" % (mode, _MODES))
        self.point = point
        self.mode = mode
        self.error_type = _resolve_error(error) if mode == "error" else None
        self.delay_s = float(delay_s)
        self.after = int(after)
        self.times = None if times is None else int(times)
        self.prob = float(prob)
        self.message = message
        self.hits = 0   # eligible matches seen (post-`after`)
        self.fired = 0  # injections actually delivered

    def __repr__(self):
        return ("FaultSpec(%s=%s, after=%d, times=%s, prob=%g, "
                "hits=%d, fired=%d)" % (
                    self.point, self.mode, self.after, self.times,
                    self.prob, self.hits, self.fired))


class FaultAction:
    """A caller-applied injection (mode=``corrupt``): the site hands its
    outbound bytes through :meth:`corrupt` and sends the mangled copy —
    simulating on-the-wire corruption without touching the socket."""

    __slots__ = ("spec", "_rng")

    def __init__(self, spec: FaultSpec, rng: random.Random):
        self.spec = spec
        self._rng = rng

    def corrupt(self, data: bytes) -> bytes:
        """Flip the leading byte (framing magic — the corruption is
        GUARANTEED to be detectable as a protocol violation, never a
        silent payload mutation) plus a seeded handful elsewhere."""
        if not data:
            return data
        buf = bytearray(data)
        buf[0] ^= 0xFF
        for _ in range(min(4, len(buf) // 256)):
            i = self._rng.randrange(len(buf))
            buf[i] ^= 0xFF
        return bytes(buf)


class FaultPlan:
    """An armed set of :class:`FaultSpec` — what :data:`active` points at.

    ``faultpoint(name, **ctx)`` is the single entry every site calls
    once its is-None gate passed: it matches the specs for ``name`` in
    order, applies deterministic ``after``/``times``/``prob`` arming,
    then performs the injection (sleep, raise, kill) or returns the
    :class:`FaultAction` for caller-applied modes.  Returns ``None``
    when nothing fired.
    """

    def __init__(self, specs: Sequence[FaultSpec], seed: int = 0):
        self.seed = int(seed)
        self._specs: Dict[str, List[FaultSpec]] = {}
        for s in specs:
            self._specs.setdefault(s.point, []).append(s)
        self._rngs: Dict[str, random.Random] = {
            point: random.Random((self.seed, point).__repr__())
            for point in self._specs
        }
        self._lock = threading.Lock()

    @property
    def points(self) -> List[str]:
        return sorted(self._specs)

    def triggers(self) -> Dict[str, int]:
        """Fired-injection counts per point (the plan-local view of
        ``faults_injected_total``)."""
        with self._lock:
            return {
                point: sum(s.fired for s in specs)
                for point, specs in self._specs.items()
            }

    # ------------------------------------------------------------------
    def faultpoint(self, name: str, **ctx) -> Optional[FaultAction]:
        """One hit of fault point ``name``.  May sleep, raise, or kill;
        returns a :class:`FaultAction` for caller-applied modes."""
        specs = self._specs.get(name)
        if not specs:
            return None
        rng = self._rngs[name]
        action: Optional[FaultAction] = None
        to_raise = None
        delay = 0.0
        kill_pid = None
        with self._lock:
            for s in specs:
                s.hits += 1
                if s.hits <= s.after:
                    continue
                if s.times is not None and s.fired >= s.times:
                    continue  # healed
                if s.prob < 1.0 and rng.random() >= s.prob:
                    continue
                s.fired += 1
                FAULTS_INJECTED.labels(point=name).inc()
                if s.mode == "delay":
                    delay += s.delay_s
                elif s.mode == "error":
                    to_raise = s.error_type(
                        s.message
                        or "injected fault at %r (%s)"
                        % (name, s.error_type.__name__))
                elif s.mode == "corrupt":
                    action = FaultAction(s, rng)
                elif s.mode == "kill":
                    kill_pid = ctx.get("pid")
        if delay > 0:
            time.sleep(delay)
        if kill_pid is not None:
            try:
                os.kill(int(kill_pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass  # already gone: the failure it simulates anyway
        if to_raise is not None:
            raise to_raise
        return action


# ---------------------------------------------------------------------------
# arming
# ---------------------------------------------------------------------------
def arm(specs, seed: int = 0) -> FaultPlan:
    """Install ``specs`` (FaultSpec list, spec-string, or a prebuilt
    plan) as the process-global plan and return it."""
    global active
    if isinstance(specs, FaultPlan):
        plan = specs
    elif isinstance(specs, str):
        plan = parse_plan(specs, seed=seed)
    else:
        plan = FaultPlan(list(specs), seed=seed)
    active = plan
    return plan


def disarm() -> None:
    """Remove the global plan (the gates go back to one is-None check)."""
    global active
    active = None


class _Armed:
    """``with faults.armed("..."):`` — arm for a scope, always disarm."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def __enter__(self) -> FaultPlan:
        return self.plan

    def __exit__(self, *exc):
        disarm()
        return False


def armed(specs, seed: int = 0) -> _Armed:
    """Context-manager form of :func:`arm` (tests: injection can never
    leak past the ``with`` block, even on assertion failure)."""
    return _Armed(arm(specs, seed=seed))


# ---------------------------------------------------------------------------
# the PADDLE_TPU_FAULTS grammar
# ---------------------------------------------------------------------------
def parse_plan(text: str, seed: int = 0) -> FaultPlan:
    """``point=mode[:arg][,key=val...]`` entries joined by ``;``.

    * ``wire.send=error:ConnectionError,times=2``
    * ``ps.pull=delay:0.05,after=3``
    * ``wire.send=corrupt,times=1`` / ``fleet.dispatch=kill,after=10``
    * a ``seed=N`` entry sets the plan seed (env arming determinism).
    """
    specs: List[FaultSpec] = []
    for entry in text.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if entry.startswith("seed="):
            seed = int(entry[5:])
            continue
        point, _, rhs = entry.partition("=")
        if not rhs:
            raise ValueError("fault entry %r has no '=mode'" % entry)
        parts = rhs.split(",")
        mode, _, arg = parts[0].partition(":")
        kw: Dict[str, object] = {}
        if mode == "error" and arg:
            kw["error"] = arg
        elif mode == "delay":
            kw["delay_s"] = float(arg or 0.01)
        elif arg:
            raise ValueError("mode %r takes no ':' argument" % mode)
        for opt in parts[1:]:
            k, _, v = opt.partition("=")
            k = k.strip()
            if k == "times":
                kw["times"] = int(v)
            elif k == "after":
                kw["after"] = int(v)
            elif k == "prob":
                kw["prob"] = float(v)
            elif k == "message":
                kw["message"] = v
            else:
                raise ValueError("unknown fault option %r" % k)
        specs.append(FaultSpec(point.strip(), mode.strip(), **kw))
    return FaultPlan(specs, seed=seed)


def arm_from_env(env: Optional[Dict[str, str]] = None) -> Optional[FaultPlan]:
    """Arm from ``PADDLE_TPU_FAULTS`` (``PADDLE_TPU_FAULTS_SEED`` sets
    the seed); returns the plan or None when the var is unset/empty.
    Called once at import so a launched child arrives pre-armed."""
    env = env if env is not None else os.environ
    text = env.get("PADDLE_TPU_FAULTS", "").strip()
    if not text:
        return None
    seed = int(env.get("PADDLE_TPU_FAULTS_SEED", "0"))
    return arm(parse_plan(text, seed=seed))


arm_from_env()
