"""Shared retry policy: exponential backoff, full jitter, deadline-debited
budgets.

The reference stack retried RPCs with a bounded loop and a deadline
(grpc_client.cc); this repo grew three ad-hoc copies of that loop (the
fleet balancer's requeue countdown, the Communicator's push retry, the
PSClient connect loop).  ``RetryPolicy`` replaces them with one
semantics:

* **Exponential backoff with full jitter** — attempt *k* may sleep up
  to ``base * multiplier**(k-1)`` (capped at ``max_delay_s``), and the
  actual sleep is drawn uniformly from ``[0, that]`` ("full jitter",
  the AWS-architecture result: decorrelated retries don't re-storm the
  server that just failed).
* **A budget per request, debited against the remaining deadline** —
  ``policy.budget(deadline=...)`` hands out retries only while both the
  attempt count AND the caller's deadline have room; a retry whose
  backoff could not complete before the deadline is refused outright
  (fail fast with the real error, never burn the caller's last
  milliseconds sleeping).
* **Accounting** — every granted retry increments
  ``retry_attempts_total{op=...}`` after its backoff sleep.

Usage::

    policy = RetryPolicy(max_attempts=4, base_delay_s=0.05)
    budget = policy.budget(deadline=deadline, op="ps.pull")
    while True:
        try:
            return call()
        except TransientError:
            if not budget.backoff():
                raise
"""
from __future__ import annotations

import random
import time
from typing import Callable, Optional

from paddle_tpu.faults.metrics import RETRY_ATTEMPTS

__all__ = ["RetryPolicy", "RetryBudget"]


class RetryPolicy:
    """Immutable retry shape; :meth:`budget` mints per-request state.

    ``max_attempts``: total call attempts allowed (1 = never retry);
    ``None`` = unbounded by count (deadline-bounded callers only).
    ``sleep``: injectable for tests (defaults to ``time.sleep``).
    ``seed``: seeds the jitter draw — chaos tests replay exactly.
    """

    def __init__(self, max_attempts: Optional[int] = 3,
                 base_delay_s: float = 0.05,
                 multiplier: float = 2.0,
                 max_delay_s: float = 2.0,
                 jitter: bool = True,
                 seed: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep):
        if max_attempts is not None and max_attempts < 1:
            raise ValueError("max_attempts must be >= 1 or None")
        self.max_attempts = max_attempts
        self.base_delay_s = float(base_delay_s)
        self.multiplier = float(multiplier)
        self.max_delay_s = float(max_delay_s)
        self.jitter = bool(jitter)
        self._seed = seed
        self._sleep = sleep

    def delay_bound(self, attempt: int) -> float:
        """Max sleep before retry number ``attempt`` (1-based)."""
        return min(self.max_delay_s,
                   self.base_delay_s * self.multiplier ** (attempt - 1))

    def budget(self, deadline: Optional[float] = None,
               op: str = "retry") -> "RetryBudget":
        """Per-request retry state.  ``deadline``: ``time.monotonic()``
        value the whole request must finish by."""
        return RetryBudget(self, deadline, op)


class RetryBudget:
    """The mutable half: one request's remaining retries.

    Not thread-safe — a budget belongs to one request on one thread,
    exactly like the deadline it debits against.
    """

    __slots__ = ("policy", "deadline", "op", "attempts", "_rng")

    def __init__(self, policy: RetryPolicy, deadline: Optional[float],
                 op: str):
        self.policy = policy
        self.deadline = deadline
        self.op = op
        self.attempts = 1  # the initial call is attempt #1
        self._rng = (random.Random(policy._seed)
                     if policy._seed is not None else random)

    def remaining_s(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()

    def backoff(self) -> bool:
        """One failed attempt: sleep the jittered backoff and grant a
        retry (True), or refuse (False) because the attempt budget is
        spent or the remaining deadline cannot absorb the backoff —
        the caller re-raises its real error."""
        p = self.policy
        if p.max_attempts is not None and self.attempts >= p.max_attempts:
            return False
        delay = p.delay_bound(self.attempts)
        if p.jitter:
            delay = self._rng.uniform(0.0, delay)
        remaining = self.remaining_s()
        if remaining is not None and delay >= remaining:
            return False  # the deadline has no room for this retry
        if delay > 0:
            p._sleep(delay)
        self.attempts += 1
        RETRY_ATTEMPTS.labels(op=self.op).inc()
        return True

    def call(self, fn, retryable=(Exception,)):
        """Run ``fn`` under this budget: retry on ``retryable``, re-raise
        the last error when the budget refuses."""
        while True:
            try:
                return fn()
            except retryable:
                if not self.backoff():
                    raise
