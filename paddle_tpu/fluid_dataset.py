"""Dataset pipeline for the trainer runtime (CTR-style slot data).

Reference: python/paddle/fluid/dataset.py (DatasetFactory:21,
InMemoryDataset:269, QueueDataset:575) over the C++ MultiSlot data feed
(paddle/fluid/framework/data_feed.cc, data_set.cc).  The parse hot loop
runs in C++ (paddle_tpu/native/ multislot_parse); sparse slots become the
padded+length encoding, dense slots dense batches.

Usage (reference style):

    dataset = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    dataset.set_use_var([ids_var, label_var])
    dataset.set_batch_size(32)
    dataset.set_filelist(["part-0", "part-1"])
    dataset.load_into_memory()
    dataset.global_shuffle()
    exe.train_from_dataset(program, dataset, fetch_list=[loss])
"""
from __future__ import annotations

import random
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from paddle_tpu import native
from paddle_tpu.core import types as core_types

__all__ = ["DatasetFactory", "InMemoryDataset", "QueueDataset"]


class DatasetFactory:
    """reference: dataset.py:21."""

    def create_dataset(self, datafeed_class: str = "QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError("unknown dataset class %r" % datafeed_class)


class DatasetBase:
    def __init__(self):
        self._batch_size = 1
        self._use_vars = []
        self._filelist: List[str] = []
        self._thread_num = 1
        self._pipe_command = "cat"
        self._hdfs_config = None

    # --- reference config surface ---
    def set_batch_size(self, batch_size: int):
        self._batch_size = batch_size

    def set_use_var(self, var_list: Sequence):
        self._use_vars = list(var_list)

    def set_filelist(self, filelist: Sequence[str]):
        self._filelist = list(filelist)

    def set_thread(self, thread_num: int):
        self._thread_num = thread_num

    def set_pipe_command(self, pipe_command: str):
        self._pipe_command = pipe_command  # preprocessing pipes are N/A here

    def set_hdfs_config(self, fs_name, fs_ugi):
        self._hdfs_config = (fs_name, fs_ugi)

    # --- parsing ---
    def _parse_file(self, path: str):
        """One file -> per-slot (values, counts) via the native parser."""
        with open(path, "rb") as f:
            text = f.read()
        n_lines, slots = native.parse_multislot(text, len(self._use_vars))
        return n_lines, slots

    def _batches_from(self, lines) -> Iterator[Dict[str, np.ndarray]]:
        """lines: list of per-line samples [(slot values list) per slot]."""
        bs = self._batch_size
        for start in range(0, len(lines) - len(lines) % bs, bs):
            chunk = lines[start : start + bs]
            feed = {}
            for si, var in enumerate(self._use_vars):
                dtype = core_types.np_dtype(var.dtype)
                rows = [ln[si] for ln in chunk]
                lens = np.array([len(r) for r in rows], np.int32)
                width = int(lens.max()) if len(lens) else 0
                if getattr(var, "lod_level", 0) and var.lod_level > 0:
                    padded = np.zeros((bs, width), dtype)
                    for i, r in enumerate(rows):
                        padded[i, : len(r)] = np.asarray(r, dtype)
                    feed[var.name] = padded
                    feed[var.name + "_seq_len"] = lens
                else:
                    feed[var.name] = np.asarray(rows, dtype).reshape(bs, -1)
            yield feed

    @staticmethod
    def _to_lines(n_lines, slots):
        lines = []
        offs = [0] * len(slots)
        for i in range(n_lines):
            row = []
            for si, (values, counts) in enumerate(slots):
                n = int(counts[i])
                row.append(values[offs[si] : offs[si] + n])
                offs[si] += n
            lines.append(row)
        return lines


class InMemoryDataset(DatasetBase):
    """reference: dataset.py:269."""

    def __init__(self):
        super().__init__()
        self._lines = []

    def load_into_memory(self):
        self._lines = []
        for path in self._filelist:
            n, slots = self._parse_file(path)
            self._lines.extend(self._to_lines(n, slots))

    def local_shuffle(self, seed: Optional[int] = None):
        random.Random(seed).shuffle(self._lines)

    def global_shuffle(self, fleet=None, seed: Optional[int] = None):
        """With a fleet handle the reference shuffles across trainers; the
        TPU build shards files per worker (launcher) so a local shuffle of
        this worker's lines is the equivalent step."""
        self.local_shuffle(seed)

    def release_memory(self):
        self._lines = []

    def get_memory_data_size(self, fleet=None):
        return len(self._lines)

    def __iter__(self):
        return self._batches_from(self._lines)


class QueueDataset(DatasetBase):
    """reference: dataset.py:575 — streaming, file at a time."""

    def __iter__(self):
        for path in self._filelist:
            n, slots = self._parse_file(path)
            yield from self._batches_from(self._to_lines(n, slots))
