"""Unified runtime flag registry (reference: the gflags tier —
paddle/fluid/platform/init.cc InitGflags + python/paddle/fluid/
__init__.py __bootstrap__'s read_env_flags list; flags are set via
``FLAGS_*`` environment variables or programmatically).

Every flag has a typed default and a docstring; point-of-use code reads
through ``flags.get_flags`` so environment overrides, ``set_flags``
calls, and defaults resolve in one place.  The reference's GPU-specific
allocator/cudnn knobs map onto their XLA/PJRT equivalents where one
exists and are accepted-but-inert (with their mapping documented)
otherwise — the same contract as BuildStrategy's XLA-subsumed knobs.
"""
from __future__ import annotations

import os
from typing import Any, Dict

__all__ = ["DEFINE_flag", "get_flags", "set_flags", "flag_doc"]

_REGISTRY: Dict[str, dict] = {}
_OVERRIDES: Dict[str, Any] = {}


def DEFINE_flag(name: str, default, doc: str, mapping: str = ""):
    """Register a flag (the gflags DEFINE_* analog)."""
    _REGISTRY[name] = {"default": default, "doc": doc, "mapping": mapping,
                       "type": type(default)}


def _coerce(value, ty):
    if ty is bool:
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes")
        return bool(value)
    return ty(value)


def get_flags(names):
    """Resolve flags: set_flags() override > FLAGS_* env > default.
    Accepts one name or a list; returns {name: value}."""
    single = isinstance(names, str)
    out = {}
    for name in [names] if single else names:
        key = name if name.startswith("FLAGS_") else "FLAGS_" + name
        if key not in _REGISTRY:
            raise KeyError("unknown flag %r (known: %s)"
                           % (key, sorted(_REGISTRY)))
        spec = _REGISTRY[key]
        if key in _OVERRIDES:
            out[key] = _OVERRIDES[key]
        elif key in os.environ:
            out[key] = _coerce(os.environ[key], spec["type"])
        else:
            out[key] = spec["default"]
    return out


def set_flags(flags: Dict[str, Any]):
    """Programmatic override (reference: fluid.set_flags).  Also mirrors
    into the environment so point-of-use os.environ reads agree."""
    for name, value in flags.items():
        key = name if name.startswith("FLAGS_") else "FLAGS_" + name
        if key not in _REGISTRY:
            raise KeyError("unknown flag %r" % key)
        spec = _REGISTRY[key]
        _OVERRIDES[key] = _coerce(value, spec["type"])
        if spec["type"] is bool:
            os.environ[key] = "1" if _OVERRIDES[key] else "0"
        else:
            os.environ[key] = str(_OVERRIDES[key])


def flag_doc(name: str) -> str:
    key = name if name.startswith("FLAGS_") else "FLAGS_" + name
    spec = _REGISTRY[key]
    extra = (" [maps to: %s]" % spec["mapping"]) if spec["mapping"] else ""
    return "%s (default %r)%s" % (spec["doc"], spec["default"], extra)


# ---------------------------------------------------------------------------
# the registry (reference list: python/paddle/fluid/__init__.py
# __bootstrap__ read_env_flags + gpu-only tail)
# ---------------------------------------------------------------------------
DEFINE_flag("FLAGS_check_nan_inf", False,
            "check every fetched/updated tensor for nan/inf after the "
            "compiled step (module-boundary analog of the per-op check)",
            "executor.py run()")
DEFINE_flag("FLAGS_allow_place_fallback", False,
            "silently fall back to CPU when the requested device is "
            "unavailable instead of raising",
            "executor.py _device()")
DEFINE_flag("FLAGS_fraction_of_gpu_memory_to_use", 0.9,
            "fraction of device memory the process may claim",
            "XLA_PYTHON_CLIENT_MEM_FRACTION (memory.py seeds it)")
DEFINE_flag("FLAGS_eager_delete_tensor_gb", 0.0,
            "reference GC threshold; XLA buffer assignment owns tensor "
            "lifetime on this build (accepted, inert)")
DEFINE_flag("FLAGS_allocator_strategy", "auto_growth",
            "reference allocator choice; PJRT's BFC allocator is the "
            "only allocator here (accepted, inert)")
DEFINE_flag("FLAGS_cudnn_deterministic", True,
            "deterministic kernels; XLA is deterministic by default "
            "(accepted, inert)")
DEFINE_flag("FLAGS_benchmark", False,
            "reference per-op benchmark mode; use profiler.py / "
            "jax.profiler traces (accepted, inert)")
DEFINE_flag("FLAGS_use_mkldnn", False,
            "reference CPU fastpath; XLA owns CPU codegen "
            "(accepted, inert)")
DEFINE_flag("FLAGS_paddle_num_threads", 1,
            "reference CPU op threads; maps to host batch-prefetch "
            "depth (TrainerDesc.set_thread)")
DEFINE_flag("FLAGS_init_allocated_mem", False,
            "poison fresh allocations; XLA buffers are always "
            "zero/overwritten before read (accepted, inert)")
DEFINE_flag("FLAGS_limit_of_tmp_allocation", -1,
            "reference temp-allocator cap (accepted, inert)")
DEFINE_flag("FLAGS_rpc_deadline", 180000,
            "PS RPC deadline in ms", "distributed/ps.py socket timeouts")
DEFINE_flag("FLAGS_rpc_retry_times", 3,
            "PS send retries before surfacing the error",
            "distributed/communicator.py max_retries")
