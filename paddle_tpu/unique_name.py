"""Unique name generator (reference: python/paddle/fluid/unique_name.py)."""
from __future__ import annotations

import collections
import contextlib

__all__ = ["generate", "guard", "guard_prefix", "switch"]


class UniqueNameGenerator:
    def __init__(self):
        self.ids = collections.defaultdict(int)
        self.prefix = ""

    def __call__(self, key: str) -> str:
        key = self.prefix + key
        i = self.ids[key]
        self.ids[key] += 1
        return "%s_%d" % (key, i)


_generator = UniqueNameGenerator()


def generate(key: str) -> str:
    return _generator(key)


def switch(new_generator=None):
    global _generator
    prev = _generator
    _generator = new_generator or UniqueNameGenerator()
    return prev


@contextlib.contextmanager
def guard(new_generator=None):
    prev = switch(new_generator)
    try:
        yield
    finally:
        switch(prev)


@contextlib.contextmanager
def guard_prefix(prefix: str):
    old = _generator.prefix
    _generator.prefix = _generator.prefix + prefix + "/"
    try:
        yield
    finally:
        _generator.prefix = old
