"""Recurrent ops: dynamic_lstm / dynamic_gru over padded+length batches.

Reference: paddle/fluid/operators/lstm_op.cc + math/lstm_compute (gate
order i,c,f,o per lstm_op.cc docs: W_x arranged {W_ix,W_cx,W_fx,W_ox}),
gru_op.cc + math/gru_compute (update u, reset r, candidate c).  The
reference iterates LoD-batched timesteps with per-step GEMMs; TPU version
is a `lax.scan` whose per-step math is identical, over the dense
padded encoding (ops/sequence_ops.py docstring), with padding masked so
results match the ragged reference exactly.

Differentiable through the generic vjp grad kernel (scan transposes).
"""
from __future__ import annotations

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import maybe, one

def _act(name):
    import jax
    import jax.numpy as jnp

    return {
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "relu": jax.nn.relu,
        "identity": lambda x: x,
    }[name]


def _lens(inputs, x, T):
    import jax.numpy as jnp

    seq_len = maybe(inputs, "SeqLen")
    if seq_len is None:
        return jnp.full((x.shape[0],), T, dtype="int32")
    return seq_len


@register_op("dynamic_lstm", no_grad_set={"SeqLen"})
def dynamic_lstm(inputs, attrs):
    """Input [B, T, 4D] (pre-projected, reference requires the x->4D fc
    done outside, lstm_op.cc), Weight [D, 4D] hidden-to-gates, Bias
    [1, 4D] (+[1, 3D] peephole tail when use_peepholes).

    Outputs Hidden [B, T, D], Cell [B, T, D].
    """
    import jax
    import jax.numpy as jnp

    x = one(inputs, "Input")
    w = one(inputs, "Weight")
    bias = maybe(inputs, "Bias")
    h0 = maybe(inputs, "H0")
    c0 = maybe(inputs, "C0")
    B, T, D4 = x.shape
    D = D4 // 4
    use_peepholes = attrs.get("use_peepholes", True)
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cell_act = _act(attrs.get("cell_activation", "tanh"))
    cand_act = _act(attrs.get("candidate_activation", "tanh"))
    is_reverse = attrs.get("is_reverse", False)

    if bias is not None:
        b_gate = bias[..., :D4].reshape(1, D4)
        peep = bias[..., D4:].reshape(-1) if (use_peepholes and bias.shape[-1] > D4) else None
    else:
        b_gate, peep = jnp.zeros((1, D4), x.dtype), None
    w_ic = peep[:D] if peep is not None else None
    w_fc = peep[D : 2 * D] if peep is not None else None
    w_oc = peep[2 * D :] if peep is not None else None

    h_init = h0 if h0 is not None else jnp.zeros((B, D), x.dtype)
    c_init = c0 if c0 is not None else jnp.zeros((B, D), x.dtype)
    lens = _lens(inputs, x, T)

    xs = jnp.swapaxes(x, 0, 1)  # [T, B, 4D]
    if is_reverse:
        xs = xs[::-1]
    steps = jnp.arange(T)

    def body(carry, inp):
        h, c = carry
        xt, t = inp
        gates = xt + h @ w + b_gate  # [B, 4D]
        gi, gc, gf, go = jnp.split(gates, 4, axis=-1)  # reference order i,c,f,o
        if w_ic is not None:
            gi = gi + c * w_ic
            gf = gf + c * w_fc
        i = gate_act(gi)
        f = gate_act(gf)
        cand = cand_act(gc)
        c_new = f * c + i * cand
        if w_oc is not None:
            go = go + c_new * w_oc
        o = gate_act(go)
        h_new = o * cell_act(c_new)
        # padding: hold state, zero the emitted output
        tt = (T - 1 - t) if is_reverse else t
        valid = (tt < lens)[:, None]
        h_keep = jnp.where(valid, h_new, h)
        c_keep = jnp.where(valid, c_new, c)
        mask = valid.astype(x.dtype)
        return (h_keep, c_keep), (h_new * mask, c_new * mask)

    (_, _), (hs, cs) = jax.lax.scan(body, (h_init, c_init), (xs, steps))
    if is_reverse:
        hs, cs = hs[::-1], cs[::-1]
    return {"Hidden": jnp.swapaxes(hs, 0, 1), "Cell": jnp.swapaxes(cs, 0, 1)}


@register_op("dynamic_gru", no_grad_set={"SeqLen"})
def dynamic_gru(inputs, attrs):
    """Input [B, T, 3D] pre-projected, Weight [D, 3D] ({W_u,W_r} first 2D,
    W_c last D), Bias [1, 3D] (reference gru_op.cc).

    Output Hidden [B, T, D].
    """
    import jax
    import jax.numpy as jnp

    x = one(inputs, "Input")
    w = one(inputs, "Weight")
    bias = maybe(inputs, "Bias")
    h0 = maybe(inputs, "H0")
    B, T, D3 = x.shape
    D = D3 // 3
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cand_act = _act(attrs.get("activation", "tanh"))
    is_reverse = attrs.get("is_reverse", False)

    b = bias.reshape(1, D3) if bias is not None else jnp.zeros((1, D3), x.dtype)
    w_gate = w[:, : 2 * D]  # [D, 2D]
    w_cand = w[:, 2 * D :]  # [D, D]
    h_init = h0 if h0 is not None else jnp.zeros((B, D), x.dtype)
    lens = _lens(inputs, x, T)

    xs = jnp.swapaxes(x, 0, 1)
    if is_reverse:
        xs = xs[::-1]
    steps = jnp.arange(T)

    def body(h, inp):
        xt, t = inp
        xg = xt + b
        x_ur, x_c = xg[..., : 2 * D], xg[..., 2 * D :]
        ur = gate_act(x_ur + h @ w_gate)
        u, r = jnp.split(ur, 2, axis=-1)
        cand = cand_act(x_c + (r * h) @ w_cand)
        # reference gru_compute: h_new = u*h + (1-u)*cand
        h_new = u * h + (1.0 - u) * cand
        tt = (T - 1 - t) if is_reverse else t
        valid = (tt < lens)[:, None]
        h_keep = jnp.where(valid, h_new, h)
        return h_keep, h_new * valid.astype(x.dtype)

    _, hs = jax.lax.scan(body, h_init, (xs, steps))
    if is_reverse:
        hs = hs[::-1]
    return {"Hidden": jnp.swapaxes(hs, 0, 1)}


@register_op("dynamic_lstmp", no_grad_set={"SeqLen"})
def dynamic_lstmp(inputs, attrs):
    """LSTM with recurrent projection (reference: lstmp_op.cc) — Input
    [B, T, 4D] pre-projected, Weight [P, 4D] projection-to-gates,
    ProjWeight [D, P]; the recurrent state is the P-dim projection.
    Outputs Projection [B, T, P], Cell [B, T, D]."""
    import jax
    import jax.numpy as jnp

    x = one(inputs, "Input")
    w = one(inputs, "Weight")  # [P, 4D]
    w_proj = one(inputs, "ProjWeight")  # [D, P]
    bias = maybe(inputs, "Bias")
    B, T, D4 = x.shape
    D = D4 // 4
    P = w_proj.shape[1]
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cell_act = _act(attrs.get("cell_activation", "tanh"))
    cand_act = _act(attrs.get("candidate_activation", "tanh"))
    proj_act = _act(attrs.get("proj_activation", "tanh"))
    use_peepholes = attrs.get("use_peepholes", True)
    if bias is not None:
        b_gate = bias[..., :D4].reshape(1, D4)
        peep = bias[..., D4:].reshape(-1) if (use_peepholes and bias.shape[-1] > D4) else None
    else:
        b_gate, peep = jnp.zeros((1, D4), x.dtype), None
    w_ic = peep[:D] if peep is not None else None
    w_fc = peep[D:2 * D] if peep is not None else None
    w_oc = peep[2 * D:] if peep is not None else None
    lens = _lens(inputs, x, T)
    r_init = jnp.zeros((B, P), x.dtype)
    c_init = jnp.zeros((B, D), x.dtype)
    xs = jnp.swapaxes(x, 0, 1)

    def body(carry, inp):
        r, c = carry
        xt, t = inp
        gates = xt + r @ w + b_gate
        gi, gc, gf, go = jnp.split(gates, 4, axis=-1)
        if w_ic is not None:
            gi = gi + c * w_ic
            gf = gf + c * w_fc
        i = gate_act(gi)
        f = gate_act(gf)
        c_new = f * c + i * cand_act(gc)
        if w_oc is not None:
            go = go + c_new * w_oc
        o = gate_act(go)
        h = o * cell_act(c_new)
        r_new = proj_act(h @ w_proj)
        active = (t < lens)[:, None]
        r_out = jnp.where(active, r_new, r)
        c_out = jnp.where(active, c_new, c)
        return (r_out, c_out), (r_out, c_out)

    (_, _), (rs, cs) = jax.lax.scan(body, (r_init, c_init), (xs, jnp.arange(T)))
    return {"Projection": jnp.swapaxes(rs, 0, 1), "Cell": jnp.swapaxes(cs, 0, 1)}
