"""Math ops: matmul/mul, broadcasting elementwise, reductions, scale, sum.

Reference kernels: paddle/fluid/operators/matmul_op.cc, mul_op.cc,
operators/elementwise/*, operators/reduce_ops/*.  Here each is a pure JAX
function; XLA maps matmuls onto the MXU and fuses the elementwise chains
(the reference needed hand-written fused_elemwise_activation kernels,
operators/fused/ — XLA does this automatically).
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import one


def _jnp():
    import jax.numpy as jnp

    return jnp


# ---------------------------------------------------------------------------
# matmul / mul
# ---------------------------------------------------------------------------
@register_op("matmul")
def matmul(inputs, attrs):
    jnp = _jnp()
    x, y = one(inputs, "X"), one(inputs, "Y")
    tx, ty = attrs.get("transpose_X", False), attrs.get("transpose_Y", False)
    alpha = attrs.get("alpha", 1.0)
    if tx:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if ty:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": out}


@register_op("mul")
def mul(inputs, attrs):
    """FC matmul: flattens X/Y to 2-D (reference: mul_op.cc)."""
    jnp = _jnp()
    x, y = one(inputs, "X"), one(inputs, "Y")
    xnc = attrs.get("x_num_col_dims", 1)
    ync = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((int(np.prod(xs[:xnc])), int(np.prod(xs[xnc:]))))
    y2 = y.reshape((int(np.prod(ys[:ync])), int(np.prod(ys[ync:]))))
    out = x2 @ y2
    return {"Out": out.reshape(tuple(xs[:xnc]) + tuple(ys[ync:]))}


# ---------------------------------------------------------------------------
# elementwise with axis-based broadcasting (reference: elementwise_op_function.h:
# Y's dims align to X starting at `axis`)
# ---------------------------------------------------------------------------
def _bcast_y(x, y, attrs):
    jnp = _jnp()
    axis = attrs.get("axis", -1)
    if x.ndim == y.ndim or y.ndim == 0:
        return y
    if axis == -1:
        axis = x.ndim - y.ndim
    shape = [1] * x.ndim
    for i, s in enumerate(y.shape):
        shape[axis + i] = s
    return y.reshape(shape)


def _ew(name, fn):
    @register_op(name)
    def kernel(inputs, attrs, _fn=fn):
        x, y = one(inputs, "X"), one(inputs, "Y")
        return {"Out": _fn(x, _bcast_y(x, y, attrs))}

    return kernel


_ew("elementwise_add", lambda x, y: x + y)
_ew("elementwise_sub", lambda x, y: x - y)
_ew("elementwise_mul", lambda x, y: x * y)
_ew("elementwise_div", lambda x, y: x / y)
_ew("elementwise_min", lambda x, y: _jnp().minimum(x, y))
_ew("elementwise_max", lambda x, y: _jnp().maximum(x, y))
_ew("elementwise_pow", lambda x, y: x**y)
_ew("elementwise_mod", lambda x, y: x % y)
_ew("elementwise_floordiv", lambda x, y: x // y)


# ---------------------------------------------------------------------------
# scale / sum / clip
# ---------------------------------------------------------------------------
@register_op("scale")
def scale(inputs, attrs):
    x = one(inputs, "X")
    s = attrs.get("scale", 1.0)
    b = attrs.get("bias", 0.0)
    after = attrs.get("bias_after_scale", True)
    out = x * s + b if after else (x + b) * s
    return {"Out": out.astype(x.dtype)}


@register_op("sum")
def sum_op(inputs, attrs):
    """N-ary add — the reference's grad-aggregation op (operators/sum_op.cc)."""
    vals = inputs["X"]
    out = vals[0]
    for v in vals[1:]:
        out = out + v
    return {"Out": out}


@register_op("clip")
def clip(inputs, attrs):
    jnp = _jnp()
    x = one(inputs, "X")
    return {"Out": jnp.clip(x, attrs.get("min"), attrs.get("max"))}


@register_op("clip_by_norm")
def clip_by_norm(inputs, attrs):
    jnp = _jnp()
    x = one(inputs, "X")
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return {"Out": x * (max_norm / jnp.maximum(norm, max_norm))}


# ---------------------------------------------------------------------------
# unary math (reference: operators/activation_op.cc registers these too)
# ---------------------------------------------------------------------------
def _unary(name, fn):
    @register_op(name)
    def kernel(inputs, attrs, _fn=fn):
        return {"Out": _fn(one(inputs, "X"))}

    return kernel


_unary("sqrt", lambda x: _jnp().sqrt(x))
_unary("rsqrt", lambda x: 1.0 / _jnp().sqrt(x))
_unary("square", lambda x: x * x)
_unary("exp", lambda x: _jnp().exp(x))
_unary("log", lambda x: _jnp().log(x))
_unary("abs", lambda x: _jnp().abs(x))
_unary("ceil", lambda x: _jnp().ceil(x))
_unary("floor", lambda x: _jnp().floor(x))
_unary("round", lambda x: _jnp().round(x))
_unary("reciprocal", lambda x: 1.0 / x)
_unary("sign", lambda x: _jnp().sign(x))
_unary("cos", lambda x: _jnp().cos(x))
_unary("sin", lambda x: _jnp().sin(x))
_unary("logsigmoid", lambda x: -_jnp().logaddexp(0.0, -x))


@register_op("pow")
def pow_op(inputs, attrs):
    return {"Out": one(inputs, "X") ** attrs.get("factor", 1.0)}


# ---------------------------------------------------------------------------
# reductions (reference: operators/reduce_ops/)
# ---------------------------------------------------------------------------
def _reduce(name, fn):
    @register_op(name)
    def kernel(inputs, attrs, _fn=fn):
        x = one(inputs, "X")
        dims = attrs.get("dim", [0])
        keep = attrs.get("keep_dim", False)
        if attrs.get("reduce_all", False) or dims is None:
            axis = None
        else:
            if isinstance(dims, int):
                dims = [dims]
            axis = tuple(d % x.ndim for d in dims)
        out = _fn(x, axis, keep)
        return {"Out": out}

    return kernel


_reduce("reduce_sum", lambda x, a, k: _jnp().sum(x, axis=a, keepdims=k))
_reduce("reduce_mean", lambda x, a, k: _jnp().mean(x, axis=a, keepdims=k))
_reduce("reduce_max", lambda x, a, k: _jnp().max(x, axis=a, keepdims=k))
_reduce("reduce_min", lambda x, a, k: _jnp().min(x, axis=a, keepdims=k))
_reduce("reduce_prod", lambda x, a, k: _jnp().prod(x, axis=a, keepdims=k))
_reduce("reduce_all", lambda x, a, k: _jnp().all(x, axis=a, keepdims=k))
_reduce("reduce_any", lambda x, a, k: _jnp().any(x, axis=a, keepdims=k))


@register_op("mean")
def mean(inputs, attrs):
    return {"Out": _jnp().mean(one(inputs, "X"))}


# ---------------------------------------------------------------------------
# comparisons / logical (reference: operators/controlflow/compare_op.cc)
# ---------------------------------------------------------------------------
def _cmp(name, fn):
    @register_op(name, differentiable=False)
    def kernel(inputs, attrs, _fn=fn):
        x, y = one(inputs, "X"), one(inputs, "Y")
        return {"Out": _fn(x, y)}

    return kernel


_cmp("equal", lambda x, y: x == y)
_cmp("not_equal", lambda x, y: x != y)
_cmp("less_than", lambda x, y: x < y)
_cmp("less_equal", lambda x, y: x <= y)
_cmp("greater_than", lambda x, y: x > y)
_cmp("greater_equal", lambda x, y: x >= y)


def _logical(name, fn, binary=True):
    @register_op(name, differentiable=False)
    def kernel(inputs, attrs, _fn=fn, _binary=binary):
        x = one(inputs, "X")
        if _binary:
            return {"Out": _fn(x, one(inputs, "Y"))}
        return {"Out": _fn(x)}

    return kernel


_logical("logical_and", lambda x, y: _jnp().logical_and(x, y))
_logical("logical_or", lambda x, y: _jnp().logical_or(x, y))
_logical("logical_xor", lambda x, y: _jnp().logical_xor(x, y))
_logical("logical_not", lambda x: _jnp().logical_not(x), binary=False)


@register_op("isfinite", differentiable=False)
def isfinite(inputs, attrs):
    jnp = _jnp()
    x = one(inputs, "X")
    return {"Out": jnp.all(jnp.isfinite(x)).reshape(1)}
